"""Device-resident cluster state (SURVEY.md §7 hard part 6, serving
form): after the first upload, delta cycles mutate the ON-DEVICE
snapshot in place instead of rebuilding + re-uploading the cluster.

This is the scheduling analogue of what continuous-batching LLM servers
(Orca-style iteration scheduling, vLLM's paged KV state) do with model
state: keep the big arrays resident on the accelerator, apply each
cycle's churn as scatter updates, and let the host do O(churn) work per
cycle instead of O(cluster).

Per delta cycle the host:
  * normalizes + interns only the CHURNED records against a persistent
    `_Interner` (vocabulary appends; ids already burned into device
    arrays stay valid),
  * re-encodes only the churned rows into the numpy mirror
    (snapshot.py's shared row fills),
  * ships those rows (plus, when insertion/removal shifted the
    name-sorted row order, one int32 permutation per collection) and
    applies them with `kernels.assign.scatter_rows` /
    `permute_rows` — `.at[idx].set` XLA scatters over whole
    struct-of-arrays groups.

Anything the row model cannot express incrementally falls back to a
full SnapshotBuilder rebuild + re-upload, counted and reasoned:
bucket overflow (rows or any feature axis), a NEW taint (the [P, VT]
tolerated matrix gains a column for every pod), or a NEW topology key
(the [N, TK] domain matrix gains a column for every node).

Invariants (the delta-vs-rebuild parity tests pin these):
  * Row order is ALWAYS name-sorted per collection — exactly the
    canonical order the wire decoder uses — so index-based tie-breaks
    are a function of cluster STATE, not of the delta history, and a
    fallback/rebuild produces identical results.
  * Value-only churn produces arrays BYTE-IDENTICAL to a fresh
    `SnapshotBuilder.build()` of the same records (same buckets).
    Vocabulary-growing churn may assign different (opaque) intern ids
    than a fresh build; solve/score results are unaffected.
  * Node `used` rows are re-summed over the node's counted running
    pods in name order on every touch — float-identical to a rebuild,
    never drifting through += / -= pairs.
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import time
import traceback
from typing import Iterable, Mapping

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from tpusched import trace as tracing
from tpusched.config import Buckets, EngineConfig
from tpusched.kernels import queue as queue_kernels
from tpusched.kernels.assign import permute_rows, scatter_rows
from tpusched.mesh import snapshot_shardings
from tpusched.qos import pressure_of
from tpusched.snapshot import (
    ClusterSnapshot,
    SnapshotBuilder,
    SnapshotMeta,
    _fill_node_row,
    _fill_pod_row,
    _fill_running_row,
    _fill_atom_row,
    _fill_sig_row,
    _pad_node_row,
    _pad_pod_row,
    _pad_running_row,
    _snapshot_from_arrays,
)


@dataclasses.dataclass
class ApplyStats:
    """What one apply() did and what it cost on the wire to the device."""

    path: str                 # "delta" | "rebuild"
    reason: str = ""          # rebuild trigger ("" on the delta path)
    h2d_bytes: int = 0        # bytes actually shipped host->device
    rows_scattered: int = 0   # churned+pad rows written across groups
    reordered: bool = False   # a permutation gather ran
    # Wire-level churn: upsert+remove records this apply carried (the
    # cycle ledger's churn field for warm/pipeline cycles, round 18 —
    # rows_scattered counts pad rows and used-resums too, so it
    # overstates what the CLIENT changed).
    churn_records: int = 0


@dataclasses.dataclass
class WarmDelta:
    """One warm solve's dirty work, derived by DeviceSnapshot.warm_delta
    from everything applied since the last committed tableau (ROADMAP
    item 3). Index lists are positions in the CURRENT name-sorted row
    order; perms map tableau-order rows to current order (None = order
    unchanged). needs_cold forces a full tableau rebuild — vocabulary
    growth, a rebuild, or a never-built lineage."""

    needs_cold: bool = False
    reason: str = ""
    dirty_pods: "list[int] | None" = None     # pod tableau rows
    dirty_nodes: "list[int] | None" = None    # node tableau columns
    dirty_members: "list[int] | None" = None  # [running | pod] columns
    pod_perm: "np.ndarray | None" = None      # int32 [pod bucket]
    node_perm: "np.ndarray | None" = None     # int32 [node bucket]
    member_perm: "np.ndarray | None" = None   # int32 [run+pod buckets]


class _NeedsRebuild(Exception):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def _tree_nbytes(tree) -> int:
    return sum(int(l.nbytes) for l in jax.tree.leaves(tree))


def _pad_pow2(idx: list[int]) -> np.ndarray:
    """Pad a scatter index list to the next power of two by REPEATING
    the first index: bounded jit-shape set, and the duplicate writes
    carry identical row content so scatter order cannot matter."""
    n = len(idx)
    cap = 1 << max(n - 1, 0).bit_length() if n > 1 else 1
    out = np.full(cap, idx[0], np.int32)
    out[:n] = idx
    return out


class DeviceSnapshot:
    """One snapshot lineage resident on the device.

    `full_load()` takes builder-style record dicts (the kwargs
    SnapshotBuilder.add_* accept, plus 'name'; running records carry
    both 'name' and 'node'), sorts them by name, builds, and uploads.
    `apply()` upserts/removes records and updates the device arrays in
    O(churn); `snap`/`meta` always reflect the latest applied state.

    Not thread-safe: callers (the sidecar's DeviceSession) serialize
    applies per lineage.
    """

    def __init__(self, config: EngineConfig | None = None,
                 buckets: Buckets | None = None, mesh=None):
        self.config = config or EngineConfig()
        self._floor_buckets = buckets
        # Optional jax.sharding.Mesh (ROADMAP item 1): when set, the
        # lineage's device arrays live SHARDED in the canonical layout
        # (mesh.snapshot_shardings: pods over 'p', nodes over 'n', vocab
        # replicated) so one lineage can hold a cluster no single
        # device fits. Delta scatters/permutes run on the sharded
        # arrays; _repin() restores the canonical layout afterwards in
        # case the partitioner chose a different output sharding.
        self.mesh = mesh
        # Span collector for device.rebuild events; None = the process
        # default at emit time (the sidecar points this at its own
        # collector when one was injected).
        self.tracer = None
        # Raw record kwargs by name (rebuild source of truth) and the
        # normalized forms row fills consume.
        self._nodes: dict[str, dict] = {}
        self._pods: dict[str, dict] = {}
        self._running: dict[str, dict] = {}
        self._norm_nodes: dict[str, dict] = {}
        self._norm_pods: dict[str, dict] = {}
        self._norm_running: dict[str, dict] = {}
        self._run_anti: dict[str, list[int]] = {}   # name -> anti sig ids
        self._pod_pc: dict[str, dict] = {}          # name -> compiled pod
        # Name-sorted row orders (the decoder's canonical order).
        self._node_order: list[str] = []
        self._pod_order: list[str] = []
        self._run_order: list[str] = []
        # group name -> {pod name: min_member}; pdb key -> {run name: allowed}
        self._group_members: dict[str, dict[str, int]] = {}
        self._pdb_members: dict[tuple, dict[str, int]] = {}
        # Reverse maps of the PREVIOUS state (see _refresh_prev_maps).
        self._run_node_name: dict[str, str] = {}
        self._pod_group_name: dict[str, str] = {}
        self._run_pdb_key: dict[str, tuple] = {}
        self._state = None          # snapshot.BuiltState
        self._meta: SnapshotMeta | None = None
        self._device: ClusterSnapshot | None = None
        # Transfer accounting (the test/bench hook for the "no full H2D
        # in steady state" acceptance).
        self.full_uploads = 0
        self.delta_updates = 0
        self.rebuilds = 0
        self.rebuild_reasons: list[str] = []
        self.h2d_bytes_total = 0
        self.h2d_bytes_last = 0
        # Warm-start residency (ROADMAP item 3): the carried tableau
        # handle lives HERE, next to the device snapshot it was built
        # from, so its lifetime is the lineage's. The lineage token is
        # the identity a handle is pinned to — a handle surviving a
        # failover/restore onto a DIFFERENT DeviceSnapshot fails the
        # engine's `is` check and takes the cold path.
        self.warm_lineage: object = object()
        self.warm_state = None            # engine.WarmState (opaque here)
        self._warm_orders = None          # (node, pod, run) orders at sync
        self._warm_vocab = None           # (n_atoms, n_sigs) at sync
        self._warm_pressure = None        # np [pod bucket] pressure at sync
        self._warm_dirty_nodes: set[str] = set()
        self._warm_dirty_pods: set[str] = set()
        self._warm_dirty_runs: set[str] = set()
        self._warm_cold_reason: "str | None" = "never_built"
        # Warm-path accounting (the bench/prof/test hooks).
        self.warm_solves = 0
        self.cold_solves = 0
        self.incremental_solves = 0
        self.warm_cold_reasons: list[str] = []
        self.last_warm_rows = (0, 0, 0)   # (pod, node, member) dirty rows
        # Previous-cycle assignment carry (ISSUE 12, the incremental
        # warm path's seed): name-keyed so row reorders between cycles
        # cannot misroute it. Committed by the engine's warm unpack on
        # the joining thread — the same single-caller serialization
        # discipline apply() relies on.
        self._carry = None  # (pod_names, node_names, assign np, chosen np)
        # Device-resident pending queue (ISSUE 20): attached lazily so
        # lineages that never ingest pay nothing. Lives on the lineage
        # because its lifetime (and failover story) is the lineage's.
        self.pending: "DeviceQueue | None" = None

    def attach_pending(self, capacity: int = 1024,
                       bound: int | None = None) -> "DeviceQueue":
        """Create (or return) this lineage's device pending queue. The
        queue inherits the lineage's qos_gain so in-kernel priorities
        match what the solver would compute host-side."""
        if self.pending is None:
            self.pending = DeviceQueue(
                capacity=capacity, bound=bound,
                qos_gain=float(self.config.qos.qos_gain))
        return self.pending

    # -- views --------------------------------------------------------------

    @property
    def snap(self) -> ClusterSnapshot:
        if self._device is None:
            raise ValueError("DeviceSnapshot: full_load() first")
        return self._device

    @property
    def meta(self) -> SnapshotMeta:
        if self._meta is None:
            raise ValueError("DeviceSnapshot: full_load() first")
        return self._meta

    @property
    def full_bytes(self) -> int:
        """Size of one full snapshot upload at current buckets."""
        return _tree_nbytes(self.snap)

    # -- load / rebuild -----------------------------------------------------

    def full_load(self, nodes: Iterable[Mapping], pods: Iterable[Mapping],
                  running: Iterable[Mapping]) -> ApplyStats:
        """Replace all state with these records and upload."""
        self._nodes = self._keyed(nodes, "node")
        self._pods = self._keyed(pods, "pod")
        self._running = self._keyed(running, "running pod")
        self._rebuild_members()
        return self._rebuild("full_load")

    @staticmethod
    def _keyed(records: Iterable[Mapping], kind: str) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for rec in records:
            name = rec.get("name")
            if not name or name in out:
                raise ValueError(
                    f"device-resident state needs unique non-empty {kind} "
                    f"names (offending: {name!r})"
                )
            out[name] = dict(rec)
        return out

    def _rebuild_members(self) -> None:
        self._group_members = {}
        for name, rec in self._pods.items():
            g = rec.get("pod_group")
            if g:
                self._group_members.setdefault(g, {})[name] = int(
                    rec.get("pod_group_min_member", 0)
                )
        self._pdb_members = {}
        for name, rec in self._running.items():
            g = rec.get("pdb_group")
            if g:
                key = (str(rec.get("namespace", "default")) or "default", g)
                self._pdb_members.setdefault(key, {})[name] = int(
                    rec.get("pdb_disruptions_allowed", 0)
                )

    def _refresh_prev_maps(self) -> None:
        """Reverse maps the NEXT apply needs to find what a churned
        record used to reference (old node, old group, old PDB)."""
        self._run_node_name = {
            name: rec["node"] for name, rec in self._running.items()
        }
        self._pod_group_name = {
            name: rec.get("pod_group") for name, rec in self._pods.items()
            if rec.get("pod_group")
        }
        self._run_pdb_key = {}
        for key, members in self._pdb_members.items():
            for name in members:
                self._run_pdb_key[name] = key

    def _put(self, snap_np: ClusterSnapshot) -> ClusterSnapshot:
        """Upload a full snapshot — sharded in the canonical mesh layout
        when this lineage has one, single (default) device otherwise."""
        if self.mesh is not None and self.mesh.devices.size > 1:
            return jax.device_put(
                snap_np, snapshot_shardings(self.mesh, snap_np)
            )
        return jax.device_put(snap_np)

    def _repin(self, dev: ClusterSnapshot) -> ClusterSnapshot:
        """Restore the canonical mesh layout after delta scatters (the
        partitioner may emit a different output sharding for the
        scattered/permuted groups). Leaves already laid out canonically
        are untouched (device_put with a matching sharding is a no-op);
        drifted leaves move device-to-device, never back through the
        host — delta applies stay O(churn) on the H2D wire."""
        if self.mesh is None or self.mesh.devices.size <= 1:
            return dev
        return jax.device_put(dev, snapshot_shardings(self.mesh, dev))

    def _rebuild(self, reason: str) -> ApplyStats:
        """Full host rebuild + device re-upload (the fallback path).
        Buckets floor at the PREVIOUS state's buckets so a lineage's
        compile shapes never shrink mid-session (no recompile churn)."""
        floor = self._state.buckets if self._state is not None \
            else self._floor_buckets
        b = SnapshotBuilder(self.config, floor)
        self._node_order = sorted(self._nodes)
        self._pod_order = sorted(self._pods)
        self._run_order = sorted(self._running)
        for name in self._node_order:
            b.add_node(**self._nodes[name])
        for name in self._pod_order:
            b.add_pod(**self._pods[name])
        for name in self._run_order:
            rec = {k: v for k, v in self._running[name].items()
                   if k != "name"}
            b.add_running_pod(**rec)
        t0 = time.perf_counter()
        snap_np, meta, state = b.build_state()
        meta.running_names = list(self._run_order)
        self._state = state
        self._meta = meta
        # Harvest the builder's normalized records + compiled forms so
        # later incremental row re-encodes match build exactly.
        self._norm_nodes = dict(zip(self._node_order, b._nodes))
        self._norm_pods = dict(zip(self._pod_order, b._pods))
        self._norm_running = dict(zip(self._run_order, b._running))
        # Compiled forms cache only what churn touches; the build just
        # burned every row, so start empty.
        self._pod_pc = {}
        self._run_anti = {}
        self._refresh_prev_maps()
        self._device = self._put(snap_np)
        # A rebuild replaces every device array: any carried warm
        # tableau is built on the OLD arrays (and possibly old buckets/
        # vocab) — drop it so the next warm solve goes cold and
        # re-anchors on this state.
        self.invalidate_warm(reason)
        nbytes = _tree_nbytes(snap_np)
        self.full_uploads += 1
        if reason != "full_load":
            self.rebuilds += 1
            self.rebuild_reasons.append(reason)
        self.h2d_bytes_last = nbytes
        self.h2d_bytes_total += nbytes
        # Event span (round 9): a rebuild is the expensive surprise of
        # the device-resident path — it must be visible in the trace
        # ring (and flight dumps) with its trigger, not just a counter.
        (self.tracer or tracing.DEFAULT).record(
            "device.rebuild", dur_s=time.perf_counter() - t0, cat="device",
            reason=reason, h2d_bytes=nbytes,
        )
        return ApplyStats(path="rebuild", reason=reason, h2d_bytes=nbytes)

    # -- incremental apply --------------------------------------------------

    def apply(
        self,
        upsert_nodes: Iterable[Mapping] = (),
        remove_nodes: Iterable[str] = (),
        upsert_pods: Iterable[Mapping] = (),
        remove_pods: Iterable[str] = (),
        upsert_running: Iterable[Mapping] = (),
        remove_running: Iterable[str] = (),
    ) -> ApplyStats:
        if self._device is None:
            raise ValueError("DeviceSnapshot: full_load() first")
        upsert_nodes = [dict(r) for r in upsert_nodes]
        upsert_pods = [dict(r) for r in upsert_pods]
        upsert_running = [dict(r) for r in upsert_running]
        remove_nodes = list(remove_nodes)
        remove_pods = list(remove_pods)
        remove_running = list(remove_running)
        for coll, kind in ((upsert_nodes, "node"), (upsert_pods, "pod"),
                           (upsert_running, "running pod")):
            seen = set()
            for rec in coll:
                name = rec.get("name")
                if not name or name in seen:
                    raise ValueError(
                        f"delta upserts need unique non-empty {kind} names "
                        f"(offending: {name!r})"
                    )
                seen.add(name)
        # Validate BEFORE committing anything: a running pod whose node
        # is gone cannot be encoded (the fresh decoder raises the same
        # way), and raising mid-apply must not leave records and device
        # arrays disagreeing.
        nodes_after = (set(self._nodes) | {r["name"] for r in upsert_nodes}
                       ) - set(remove_nodes)
        removed_r = set(remove_running)
        upserted_r = {u["name"] for u in upsert_running}
        check = list(upsert_running)
        if remove_nodes:
            check += [rec for name, rec in self._running.items()
                      if name not in removed_r and name not in upserted_r]
        for rec in check:
            if rec["node"] not in nodes_after:
                raise ValueError(
                    f"running pod {rec.get('name')!r} references missing "
                    f"node {rec['node']!r}"
                )
        # Commit the record store FIRST: if the incremental path cannot
        # express the change, _rebuild() regenerates everything from
        # records, so any surprise below degrades to a correct (slower)
        # cycle instead of corrupt state.
        for rec in upsert_nodes:
            self._nodes[rec["name"]] = rec
        for name in remove_nodes:
            self._nodes.pop(name, None)
        for rec in upsert_pods:
            self._pods[rec["name"]] = rec
        for name in remove_pods:
            self._pods.pop(name, None)
        for rec in upsert_running:
            self._running[rec["name"]] = rec
        for name in remove_running:
            self._running.pop(name, None)
        self._rebuild_members()
        churn = (len(upsert_nodes) + len(remove_nodes) + len(upsert_pods)
                 + len(remove_pods) + len(upsert_running)
                 + len(remove_running))
        try:
            stats = self._apply_incremental(
                upsert_nodes, remove_nodes, upsert_pods, remove_pods,
                upsert_running, remove_running,
            )
        except _NeedsRebuild as e:
            stats = self._rebuild(e.reason)
        except Exception:  # noqa: BLE001 — heal, then let tests catch it
            logging.getLogger("tpusched.device_state").warning(
                "incremental delta apply failed; rebuilding this "
                "lineage:\n%s", traceback.format_exc(limit=4),
            )
            stats = self._rebuild("incremental_error")
        stats.churn_records = churn
        return stats

    def _apply_incremental(self, upsert_nodes, remove_nodes, upsert_pods,
                           remove_pods, upsert_running, remove_running
                           ) -> ApplyStats:
        st = self._state
        intr = st.interner
        bk = st.buckets
        cfg = self.config

        # Row-count capacity.
        if (len(self._pods) > bk.pods or len(self._nodes) > bk.nodes
                or len(self._running) > bk.running_pods):
            raise _NeedsRebuild("row_bucket")

        # Normalize churned records through a scratch builder: identical
        # defaulting (pods resource, namespace fallback, PDB keying) to
        # a full build.
        nb = SnapshotBuilder(cfg)
        for rec in upsert_nodes:
            nb.add_node(**rec)
        for rec in upsert_pods:
            nb.add_pod(**rec)
        for rec in upsert_running:
            nb.add_running_pod(**{k: v for k, v in rec.items()
                                  if k != "name"})
        norm_nodes = dict(zip([r["name"] for r in upsert_nodes], nb._nodes))
        norm_pods = dict(zip([r["name"] for r in upsert_pods], nb._pods))
        norm_running = dict(
            zip([r["name"] for r in upsert_running], nb._running)
        )

        # Vocabulary growth with column-wise blast radius forces a
        # rebuild: a new taint grows pods.tolerated for EVERY pod, a new
        # topology key grows nodes.domain for EVERY node.
        n_topo0 = len(intr.topo_keys)
        for rec in norm_nodes.values():
            for (k, v, e) in rec["taints"]:
                if (k, v, e) not in intr.taint_ids:
                    raise _NeedsRebuild("new_taint")

        n_atoms0, n_sigs0 = len(intr.atoms), len(intr.sigs)
        new_pcs: dict[str, dict] = {}
        for name, rec in norm_pods.items():
            pc = intr.compile_pod(rec)
            intr.intern_labels(rec["labels"])
            intr.nsid(rec["namespace"])
            new_pcs[name] = pc
            if (len(pc["req_terms"]) > bk.terms
                    or len(pc["pref_terms"]) > bk.pref_terms
                    or len(pc["ts"]) > bk.spread_constraints
                    or len(pc["ia"]) > bk.affinity_terms
                    or len(rec["labels"]) > bk.pod_labels
                    or any(len(t) > bk.term_atoms
                           for t in pc["req_terms"])
                    or any(len(t[0]) > bk.term_atoms
                           for t in pc["pref_terms"])):
                raise _NeedsRebuild("pod_feature_bucket")
        new_anti: dict[str, list[int]] = {}
        for name, rec in norm_running.items():
            sigs_of_pod, am = intr.compile_running_anti(rec)
            intr.intern_labels(rec["labels"])
            intr.nsid(rec["namespace"])
            new_anti[name] = sigs_of_pod
            if (len(sigs_of_pod) > bk.affinity_terms or am > bk.term_atoms
                    or len(rec["labels"]) > bk.pod_labels):
                raise _NeedsRebuild("running_feature_bucket")
        for rec in norm_nodes.values():
            intr.intern_labels(rec["labels"])
            if (len(rec["labels"]) > bk.node_labels
                    or len(rec["taints"]) > bk.node_taints):
                raise _NeedsRebuild("node_feature_bucket")
        # Topology-domain ids append FOREVER on a long-lived interner
        # (node relabels keep minting values), but the pairwise kernels
        # scatter domain counts into [S, N] — an id >= the node bucket
        # would be silently dropped by XLA. A fresh build compacts ids
        # to <= #nodes, so rebuild before the bucket is breached.
        new_domains: dict[int, set] = {}
        for rec in norm_nodes.values():
            for ti, tk in enumerate(intr.topo_keys):
                v = rec["labels"].get(tk)
                if v is not None and v not in intr.domain_ids[ti]:
                    new_domains.setdefault(ti, set()).add(v)
        for ti, vals in new_domains.items():
            if len(intr.domain_ids[ti]) + len(vals) > bk.nodes:
                raise _NeedsRebuild("domain_vocab")
        if len(intr.topo_keys) > n_topo0:
            raise _NeedsRebuild("new_topo_key")
        if len(intr.atoms) > bk.atoms or len(intr.sigs) > bk.signatures:
            raise _NeedsRebuild("table_bucket")
        for a in range(n_atoms0, len(intr.atoms)):
            if len(intr.atoms[a][2]) > bk.atom_values:
                raise _NeedsRebuild("atom_values_bucket")
        for s in range(n_sigs0, len(intr.sigs)):
            _, ns_scope, alist = intr.sigs[s]
            if len(alist) > bk.term_atoms or (
                    ns_scope != "*" and len(ns_scope) > bk.sig_namespaces):
                raise _NeedsRebuild("sig_bucket")

        # Groups / PDBs: new ids APPEND (a fresh build sorts names; ids
        # are opaque equality tokens so appending keeps settled pod rows
        # valid). Touched = any slot whose membership a churned record
        # enters or leaves; its value is max over current members.
        touched_groups = set()
        for rec in upsert_pods:
            g = rec.get("pod_group")
            if g:
                touched_groups.add(g)
            old_g = self._pod_group_name.get(rec["name"])
            if old_g:
                touched_groups.add(old_g)
        for name in remove_pods:
            old_g = self._pod_group_name.get(name)
            if old_g:
                touched_groups.add(old_g)
        for g in touched_groups:
            if g in self._group_members and g not in st.group_idx:
                if len(st.group_idx) >= bk.pod_groups:
                    raise _NeedsRebuild("group_bucket")
                st.group_idx[g] = len(st.group_idx)
        touched_groups &= set(st.group_idx)
        touched_pdbs = set()
        for rec in norm_running.values():
            if rec["pdb_group"] is not None:
                touched_pdbs.add(rec["pdb_group"])
        for rec in upsert_running:
            old_key = self._run_pdb_key.get(rec["name"])
            if old_key:
                touched_pdbs.add(old_key)
        for name in remove_running:
            old_key = self._run_pdb_key.get(name)
            if old_key:
                touched_pdbs.add(old_key)
        for key in touched_pdbs:
            if key in self._pdb_members and key not in st.pdb_idx:
                if len(st.pdb_idx) >= bk.pdb_groups:
                    raise _NeedsRebuild("pdb_bucket")
                st.pdb_idx[key] = len(st.pdb_idx)
        touched_pdbs &= set(st.pdb_idx)

        # Commit normalized forms + compiled caches.
        for name in remove_nodes:
            self._norm_nodes.pop(name, None)
        for name in remove_pods:
            self._norm_pods.pop(name, None)
            self._pod_pc.pop(name, None)
        for name in remove_running:
            self._norm_running.pop(name, None)
            self._run_anti.pop(name, None)
        self._norm_nodes.update(norm_nodes)
        self._norm_pods.update(norm_pods)
        self._norm_running.update(norm_running)
        self._pod_pc.update(new_pcs)
        self._run_anti.update(new_anti)

        # Churn sets. A running upsert/remove dirties its node's `used`
        # row (old node AND new node when the pod moved).
        node_churn = set(norm_nodes)
        run_churn = set(norm_running)
        pod_churn = set(norm_pods)
        for rec in upsert_running:
            node_churn.add(rec["node"])
            old_node = self._run_node_name.get(rec["name"])
            if old_node is not None:
                node_churn.add(old_node)
        for name in remove_running:
            old_node = self._run_node_name.get(name)
            if old_node is not None:
                node_churn.add(old_node)
        node_churn &= set(self._nodes)
        self._refresh_prev_maps()

        new_node_order = sorted(self._nodes)
        new_pod_order = sorted(self._pods)
        new_run_order = sorted(self._running)

        # Permutations for insertion/removal (None = steady-state
        # value churn, pure scatter).
        node_perm, node_pads = self._perm(self._node_order, new_node_order,
                                          bk.nodes)
        pod_perm, pod_pads = self._perm(self._pod_order, new_pod_order,
                                        bk.pods)
        run_perm, run_pads = self._perm(self._run_order, new_run_order,
                                        bk.running_pods)
        node_reorder = node_perm is not None
        if node_reorder:
            # Node rows moved: every running row's node_idx needs a
            # remap (one [M] int32 column, not a per-row re-encode).
            old_pos = {nm: i for i, nm in enumerate(self._node_order)}
            remap = np.full(bk.nodes, -1, np.int32)
            for new_i, nm in enumerate(new_node_order):
                if nm in old_pos:
                    remap[old_pos[nm]] = new_i

        # Reorder the numpy mirror first (fancy-index gather allocates
        # fresh arrays), then re-encode churned rows at NEW positions,
        # then pad vacated tail rows.
        for holder, perm in ((st.nodes_np, node_perm),
                             (st.pods_np, pod_perm),
                             (st.run_np, run_perm)):
            if perm is None:
                continue
            for f, arr in list(vars(holder).items()):
                setattr(holder, f, np.ascontiguousarray(arr[perm]))
        if node_reorder:
            ni = st.run_np.node_idx
            st.run_np.node_idx = np.where(
                ni >= 0, remap[ni], ni
            ).astype(np.int32)
        mirror = _snapshot_from_arrays(st.nodes_np, st.pods_np, st.run_np,
                                       st.tables)
        st.node_index = {nm: i for i, nm in enumerate(new_node_order)}
        pod_index = {nm: i for i, nm in enumerate(new_pod_order)}
        run_index = {nm: i for i, nm in enumerate(new_run_order)}

        run_by_node: dict[str, list[str]] = {}
        for name in new_run_order:
            run_by_node.setdefault(self._norm_running[name]["node"],
                                   []).append(name)
        for nm in node_churn:
            i = st.node_index[nm]
            _fill_node_row(st.nodes_np, i, self._norm_nodes[nm], intr, cfg)
            # Re-sum counted members in name order: float-identical to a
            # rebuild, never drifting through +=/-= pairs.
            for member in run_by_node.get(nm, ()):
                rrec = self._norm_running[member]
                if rrec["count_into_used"]:
                    for r, rn in enumerate(cfg.resources):
                        st.nodes_np.used[i, r] += float(
                            rrec["requests"].get(rn, 0.0)
                        )
        for nm in pod_churn:
            _fill_pod_row(st.pods_np, pod_index[nm], self._norm_pods[nm],
                          self._pod_pc[nm], intr, cfg, st.group_idx)
        for nm in run_churn:
            _fill_running_row(st.run_np, run_index[nm],
                              self._norm_running[nm], self._run_anti[nm],
                              intr, cfg, st.node_index, st.pdb_idx)
        for i in node_pads:
            _pad_node_row(st.nodes_np, i)
        for i in pod_pads:
            _pad_pod_row(st.pods_np, i)
        for i in run_pads:
            _pad_running_row(st.run_np, i)

        # New atom/sig table rows + touched group/pdb scalars.
        for a in range(n_atoms0, len(intr.atoms)):
            _fill_atom_row(st.tables, a, intr.atoms[a])
        for s in range(n_sigs0, len(intr.sigs)):
            _fill_sig_row(st.tables, s, intr.sigs[s])
        for g in touched_groups:
            members = self._group_members.get(g, {})
            st.tables.group_min[st.group_idx[g]] = (
                max(members.values()) if members else 0
            )
        for key in touched_pdbs:
            members = self._pdb_members.get(key, {})
            st.tables.pdb_allowed[st.pdb_idx[key]] = float(
                max(members.values()) if members else 0
            )

        # Device updates: permutation gathers, then row scatters.
        h2d = 0
        rows_written = 0
        dev = self._device
        nodes_dev, pods_dev, run_dev = dev.nodes, dev.pods, dev.running
        if node_perm is not None:
            nodes_dev = permute_rows(nodes_dev, node_perm)
            h2d += node_perm.nbytes
        if pod_perm is not None:
            pods_dev = permute_rows(pods_dev, pod_perm)
            h2d += pod_perm.nbytes
        if run_perm is not None:
            run_dev = permute_rows(run_dev, run_perm)
            h2d += run_perm.nbytes
        if node_reorder:
            # Ship the remapped node_idx column wholesale (int32 [M]).
            # On a mesh it must land replicated across the mesh devices
            # (the canonical running layout) — a plain device_put would
            # commit it to the default device only, and the scatter jit
            # below rejects committed inputs on mismatched device sets.
            if self.mesh is not None and self.mesh.devices.size > 1:
                ni_dev = jax.device_put(
                    st.run_np.node_idx,
                    NamedSharding(self.mesh, PartitionSpec()),
                )
            else:
                ni_dev = jax.device_put(st.run_np.node_idx)
            run_dev = dataclasses.replace(run_dev, node_idx=ni_dev)
            h2d += st.run_np.node_idx.nbytes

        def scatter(dev_tree, mirror_tree, rows):
            nonlocal h2d, rows_written
            rows = sorted(set(rows))
            if not rows:
                return dev_tree
            idx = _pad_pow2(rows)
            row_data = jax.tree.map(lambda a: a[idx], mirror_tree)
            h2d += idx.nbytes + _tree_nbytes(row_data)
            rows_written += len(rows)
            return scatter_rows(dev_tree, idx, row_data)

        nodes_dev = scatter(
            nodes_dev, mirror.nodes,
            [st.node_index[nm] for nm in node_churn] + list(node_pads),
        )
        pods_dev = scatter(
            pods_dev, mirror.pods,
            [pod_index[nm] for nm in pod_churn] + list(pod_pads),
        )
        run_dev = scatter(
            run_dev, mirror.running,
            [run_index[nm] for nm in run_churn] + list(run_pads),
        )
        atoms_dev = scatter(dev.atoms, mirror.atoms,
                            list(range(n_atoms0, len(intr.atoms))))
        sigs_dev = scatter(dev.sigs, mirror.sigs,
                           list(range(n_sigs0, len(intr.sigs))))
        group_dev = scatter(dev.group_min_member, mirror.group_min_member,
                            [st.group_idx[g] for g in touched_groups])
        pdb_dev = scatter(dev.pdb_allowed, mirror.pdb_allowed,
                          [st.pdb_idx[k] for k in touched_pdbs])

        self._device = self._repin(dataclasses.replace(
            dev, nodes=nodes_dev, pods=pods_dev, running=run_dev,
            atoms=atoms_dev, sigs=sigs_dev, group_min_member=group_dev,
            pdb_allowed=pdb_dev,
        ))
        self._node_order = new_node_order
        self._pod_order = new_pod_order
        self._run_order = new_run_order
        # Warm-start dirty accumulation (ROADMAP item 3): every name
        # whose row this apply re-encoded (including used-resummed
        # nodes) goes stale in the carried tableau. Vacated/pad rows
        # and reorders are derived from the ORDER diff at warm_delta()
        # time, so multiple applies between solves compose. Only while
        # a tableau is actually committed: lineages that never warm-
        # solve (the sidecar's DeviceSessions today) must not grow
        # these sets without bound across a long serving life.
        if self._warm_orders is not None:
            self._warm_dirty_nodes |= node_churn
            self._warm_dirty_pods |= pod_churn
            self._warm_dirty_runs |= run_churn
        self._meta = SnapshotMeta(
            node_names=list(new_node_order),
            pod_names=list(new_pod_order),
            n_nodes=len(new_node_order), n_pods=len(new_pod_order),
            n_running=len(new_run_order), buckets=bk,
            # ID order, not name order: appended mid-session groups get
            # ids past the originally-sorted ones, and group_names[i]
            # must keep naming group id i.
            group_names=[g for g, _ in sorted(st.group_idx.items(),
                                              key=lambda kv: kv[1])],
            running_names=list(new_run_order),
        )
        self.delta_updates += 1
        self.h2d_bytes_last = h2d
        self.h2d_bytes_total += h2d
        return ApplyStats(
            path="delta", h2d_bytes=h2d, rows_scattered=rows_written,
            reordered=(node_perm is not None or pod_perm is not None
                       or run_perm is not None),
        )

    # -- warm-start residency (ROADMAP item 3) ------------------------------

    def invalidate_warm(self, reason: str) -> None:
        """Drop the carried tableau AND the assignment carry: the next
        warm solve goes cold (and re-anchors the lineage), and the next
        incremental solve falls back to the bitwise path until a fresh
        carry lands. Called on every rebuild, by the host on a failed
        cycle (the unwind contract), and available to any owner whose
        fetch errored after dispatch (the conservative reset)."""
        self.warm_state = None
        self._warm_cold_reason = reason
        self._warm_orders = None
        self._warm_dirty_nodes = set()
        self._warm_dirty_pods = set()
        self._warm_dirty_runs = set()
        self._carry = None

    def warm_delta(self) -> WarmDelta:
        """Derive the dirty work accumulated since the last committed
        tableau: churned rows at their CURRENT name-sorted positions,
        rows vacated by shrinkage (now padding), one reorder perm per
        axis (tableau order -> current order, exactly the permutation
        discipline apply() uses for the snapshot arrays), and — the QoS
        temporal-locality guard — pods whose pressure drifted since the
        tableau was committed, found by one vectorized qos.pressure_of
        compare. The pressure compare is DEFENSIVE: the engine
        recomputes every pressure-dependent quantity (plugin weights,
        pop order, preemption priorities) fresh from the snapshot each
        solve, so a pressure change alone never changes tableau cells;
        the compare catches out-of-band mirror edits that bypassed
        apply(). Vocabulary growth (atoms/sigs appended by a delta)
        forces needs_cold: new vocab rows change tableau cells of
        UNCHURNED rows, which the row model cannot express."""
        if self._warm_cold_reason is not None:
            return WarmDelta(needs_cold=True, reason=self._warm_cold_reason)
        st = self._state
        bk = st.buckets
        if (len(st.interner.atoms), len(st.interner.sigs)) != self._warm_vocab:
            return WarmDelta(needs_cold=True, reason="vocab_growth")
        o_nodes, o_pods, o_runs = self._warm_orders
        node_perm, node_pads = self._perm(o_nodes, self._node_order,
                                          bk.nodes)
        pod_perm, pod_pads = self._perm(o_pods, self._pod_order, bk.pods)
        run_perm, run_pads = self._perm(o_runs, self._run_order,
                                        bk.running_pods)
        pod_index = {nm: i for i, nm in enumerate(self._pod_order)}
        run_index = {nm: i for i, nm in enumerate(self._run_order)}
        d_nodes = {st.node_index[nm] for nm in self._warm_dirty_nodes
                   if nm in st.node_index} | set(node_pads)
        d_pods = {pod_index[nm] for nm in self._warm_dirty_pods
                  if nm in pod_index} | set(pod_pads)
        d_runs = {run_index[nm] for nm in self._warm_dirty_runs
                  if nm in run_index} | set(run_pads)
        cur = np.asarray(pressure_of(st.pods_np.slo_target,
                                     st.pods_np.observed_avail))
        prev = self._warm_pressure
        prev_at_cur = prev[pod_perm] if pod_perm is not None else prev
        drift = np.nonzero((cur != prev_at_cur) & st.pods_np.valid)[0]
        d_pods |= {int(i) for i in drift}
        # A pod is both a tableau ROW and a pairwise MEMBER column; a
        # running pod is a member column only. Member axis layout is
        # [running bucket | pod bucket] (kernels.pairwise).
        d_members = {int(i) for i in d_runs} | {
            bk.running_pods + int(i) for i in d_pods
        }
        member_perm = None
        if run_perm is not None or pod_perm is not None:
            rp = run_perm if run_perm is not None else np.arange(
                bk.running_pods, dtype=np.int32)
            pp = pod_perm if pod_perm is not None else np.arange(
                bk.pods, dtype=np.int32)
            member_perm = np.concatenate([rp, bk.running_pods + pp])
        return WarmDelta(
            dirty_pods=sorted(d_pods) or None,
            dirty_nodes=sorted(d_nodes) or None,
            dirty_members=sorted(d_members) or None,
            pod_perm=pod_perm, node_perm=node_perm,
            member_perm=member_perm,
        )

    def warm_marker(self) -> "tuple[int, int]":
        """(warm_solves, incremental_solves) snapshot BEFORE a warm
        dispatch — pair with warm_path_taken to classify what the
        dispatch actually served. One authority (round 18, ISSUE 13):
        the host, the warm stream, and the ledger's warm-mix must all
        read the commit_warm counters the same way."""
        return (self.warm_solves, self.incremental_solves)

    def warm_path_taken(self, marker: "tuple[int, int]") -> str:
        """Path the dispatch since `marker` took (the ledger's
        canonical spelling): incremental | warm | cold."""
        if self.incremental_solves > marker[1]:
            return "incremental"
        if self.warm_solves > marker[0]:
            return "warm"
        return "cold"

    def commit_warm(self, state, path: str, reason: str, rows) -> None:
        """Engine callback at warm/cold dispatch time: store the new
        handle and re-anchor the dirty accumulation on the snapshot
        state the dispatched program reads."""
        st = self._state
        self.warm_state = state
        self._warm_orders = (list(self._node_order),
                             list(self._pod_order),
                             list(self._run_order))
        self._warm_vocab = (len(st.interner.atoms), len(st.interner.sigs))
        self._warm_pressure = np.array(pressure_of(
            st.pods_np.slo_target, st.pods_np.observed_avail))
        self._warm_dirty_nodes = set()
        self._warm_dirty_pods = set()
        self._warm_dirty_runs = set()
        self._warm_cold_reason = None
        self.last_warm_rows = tuple(rows)
        if path == "warm":
            self.warm_solves += 1
        elif path == "incremental":
            self.incremental_solves += 1
        else:
            self.cold_solves += 1
            self.warm_cold_reasons.append(reason)

    def commit_carry(self, pod_names, node_names, assignment, chosen,
                     ) -> None:
        """Store a completed solve's assignment as the next incremental
        cycle's seed (ISSUE 12). `pod_names`/`node_names` are the name
        orders of the snapshot that solve ran against — the carry is
        NAME-keyed, so later applies reordering rows (or a rebuild
        renumbering nodes) reroute rather than corrupt it."""
        self._carry = (list(pod_names), list(node_names),
                       np.asarray(assignment), np.asarray(chosen))

    def carry_arrays(self):
        """Map the stored carry onto the CURRENT name-sorted row order:
        (carry [pod bucket] int32 node index | -1, chosen [pod bucket]
        f32 as-of-placement scores) or None when no carry exists (never
        solved, or invalidated). Pods/nodes that vanished since the
        carried solve simply drop out (-1 = pending)."""
        if self._carry is None:
            return None
        prev_pods, prev_nodes, a, c = self._carry
        bk = self._state.buckets
        # Steady-state fast path: no row churn since the carried solve
        # (same pod AND node name orders, same buckets) means the carry
        # maps identically — skip the O(P) per-name remap loop that
        # would otherwise run on every incremental dispatch.
        if (prev_pods == self._pod_order and prev_nodes == self._node_order
                and a.shape[0] == bk.pods):
            return (np.asarray(a, np.int32).copy(),
                    np.asarray(c, np.float32).copy())
        carry = np.full(bk.pods, -1, np.int32)
        chos = np.full(bk.pods, -np.inf, np.float32)
        prev_idx = {nm: i for i, nm in enumerate(prev_pods)}
        node_now = self._state.node_index
        for i, nm in enumerate(self._pod_order):
            j = prev_idx.get(nm)
            if j is None or j >= len(a):
                continue
            n = int(a[j])
            if n < 0 or n >= len(prev_nodes):
                continue
            ni = node_now.get(prev_nodes[n], -1)
            if ni >= 0:
                carry[i] = ni
                chos[i] = np.float32(c[j])
        return carry, chos

    @staticmethod
    def _perm(old_order: list[str], new_order: list[str], bucket: int):
        """(perm int32[bucket] | None, vacated-row indices). None when
        the order is unchanged (the steady-state value-churn cycle)."""
        if old_order == new_order:
            return None, []
        old_pos = {nm: i for i, nm in enumerate(old_order)}
        perm = np.arange(bucket, dtype=np.int32)
        for i, nm in enumerate(new_order):
            perm[i] = old_pos.get(nm, i)
        pads = list(range(len(new_order), len(old_order)))
        return perm, pads


# ---------------------------------------------------------------------------
# Device-resident pending queue (ISSUE 20)
# ---------------------------------------------------------------------------


class DeviceQueue:
    """The persistent [Q] pending table: host mirror + device twin.

    The host keeps a numpy struct-of-arrays mirror plus the name<->slot
    map; every mutation (upsert / remove / park) touches ONLY the
    mirror and marks the slot dirty, and `window()` ships the dirty
    rows in one pow2-padded scatter (`_pad_pow2` + `scatter_rows`, the
    PR 2 delta discipline) before ranking — so per-cycle device traffic
    is O(mutations) and per-cycle host work never re-reads or re-sorts
    the pending set. Ranking, availability decay, and the top-W window
    slice all run in-kernel (kernels.queue.window_select).

    Times are rebased against the first-submit epoch so wall clocks
    survive the float32 table (f32 resolution at time.time() magnitudes
    is ~256s; rebased sim/wall offsets are exact to well past a sim
    day). `bound` caps admission: upsert of a NEW name into a full
    bounded queue returns False and the caller sheds (RESOURCE_EXHAUSTED
    at the rpc layer); unbounded queues grow by pow2 doubling, which
    drops the device twin for one full re-upload (bounded compile set:
    one (Q, kb) bucket pair per capacity).

    Not thread-safe: the ingest gate serializes access under its own
    lock; HostScheduler drives it single-threaded from the cycle loop.
    """

    def __init__(self, capacity: int = 1024, bound: int | None = None,
                 qos_gain: float = 1000.0):
        cap = 1 << max(int(capacity) - 1, 0).bit_length()
        self.bound = int(bound) if bound else None
        self.qos_gain = float(qos_gain)
        self._host = queue_kernels.empty_table(cap)
        self._dev = None                    # device twin; None = stale
        self._slot: dict[str, int] = {}     # name -> slot index
        self._names: list[str | None] = [None] * cap
        self._free: list[int] = list(range(cap))  # min-heap (lowest first)
        heapq.heapify(self._free)
        self._dirty: set[int] = set()
        self._epoch: float | None = None    # first-submit time rebase
        self._seq = 0                       # arrival sequence stamp
        # Profiling counters (tools/prof_components.py --queue and the
        # ingest bench read these).
        self.scatters = 0
        self.scatter_rows_total = 0
        self.windows = 0

    # -- inspection ------------------------------------------------------

    @property
    def capacity(self) -> int:
        return len(self._names)

    @property
    def depth(self) -> int:
        return len(self._slot)

    def __contains__(self, name: str) -> bool:
        return name in self._slot

    def names(self) -> list[str]:
        return list(self._slot)

    def _rebase(self, t: float) -> np.float32:
        if self._epoch is None:
            self._epoch = float(t)
        return np.float32(t - self._epoch)

    # -- mutation (host mirror only; O(1) each) --------------------------

    def upsert(self, name: str, *, base_priority: float = 0.0,
               slo_target: float = 0.0, submitted: float = 0.0,
               run_seconds: float = 0.0, parked_until: float = 0.0,
               tenant: int = 0, seq: int | None = None) -> bool:
        """Insert or update one pending row. Returns False (and changes
        nothing) when the queue is bounded and full and `name` is new —
        the admission-shed signal."""
        slot = self._slot.get(name)
        if slot is None:
            if self.bound is not None and len(self._slot) >= self.bound:
                return False
            if not self._free:
                self._grow()
            slot = heapq.heappop(self._free)
            self._slot[name] = slot
            self._names[slot] = name
        if seq is None:
            seq = self._seq
        self._seq = max(self._seq, int(seq)) + 1
        h = self._host
        h.valid[slot] = True
        h.base_priority[slot] = np.float32(base_priority)
        h.slo_target[slot] = np.float32(slo_target)
        h.submitted[slot] = self._rebase(submitted)
        h.run_seconds[slot] = np.float32(run_seconds)
        h.parked_until[slot] = self._rebase(parked_until) \
            if parked_until else np.float32(0.0)
        h.tenant[slot] = np.int32(tenant)
        h.seq[slot] = np.uint32(seq)
        self._dirty.add(slot)
        return True

    def remove(self, names: Iterable[str]) -> int:
        """Invalidate slots (bind/delete). Unknown names are ignored —
        removal is idempotent like FakeApiServer.delete_pod."""
        n = 0
        for name in names:
            slot = self._slot.pop(name, None)
            if slot is None:
                continue
            self._host.valid[slot] = False
            self._names[slot] = None
            heapq.heappush(self._free, slot)
            self._dirty.add(slot)
            n += 1
        return n

    def park(self, name: str, until: float) -> bool:
        """Backoff-park one pod: ineligible until `until` (absolute
        time, same clock as upsert/window). The row keeps its place,
        priority keeps decaying — parking masks eligibility only."""
        slot = self._slot.get(name)
        if slot is None:
            return False
        self._host.parked_until[slot] = self._rebase(until)
        self._dirty.add(slot)
        return True

    # -- device sync + window -------------------------------------------

    def _grow(self) -> None:
        old = self._host
        old_cap = len(self._names)
        new_cap = old_cap * 2
        self._host = queue_kernels.empty_table(new_cap)
        for f, arr in zip(self._host._fields, self._host):
            arr[:old_cap] = getattr(old, f)
        self._names.extend([None] * old_cap)
        for s in range(old_cap, new_cap):
            heapq.heappush(self._free, s)
        self._dev = None            # full re-upload on next flush

    def _flush(self) -> None:
        """Ship dirty mirror rows to the device twin: one pow2-padded
        scatter per cycle (or a full device_put after growth)."""
        if self._dev is None:
            self._dev = jax.device_put(
                queue_kernels.QueueTable(*[np.asarray(a) for a in self._host]))
            self._dirty.clear()
            return
        if not self._dirty:
            return
        rows = sorted(self._dirty)
        idx = _pad_pow2(rows)
        row_data = queue_kernels.QueueTable(
            *[np.ascontiguousarray(a[idx]) for a in self._host])
        self._dev = scatter_rows(self._dev, idx, row_data)
        self.scatters += 1
        self.scatter_rows_total += len(rows)
        self._dirty.clear()

    def window(self, now: float, w: int):
        """Extract the top-`w` solve window ON DEVICE: flush dirty
        rows, rank the whole table in-kernel, slice the pow2 window
        bucket, and map the returned slots back to names. Returns
        (names in pop order, n_eligible, depth) with
        len(names) == min(w, n_eligible)."""
        self._flush()
        if self._epoch is None:
            return [], 0, 0
        cap = self.capacity
        kb = queue_kernels.k_bucket(min(max(int(w), 1), cap), cap)
        win, _prio, n_elig, depth = queue_kernels.window_select(
            self._dev, self._rebase(now), self.qos_gain, kb)
        self.windows += 1
        n_elig = int(n_elig)
        take = min(int(w), n_elig, kb)
        names = []
        for s in np.asarray(win)[:take]:
            nm = self._names[int(s)]
            if nm is not None:
                names.append(nm)
        return names, n_elig, int(depth)

    def stats(self) -> dict:
        return {
            "depth": self.depth,
            "capacity": self.capacity,
            "bound": self.bound,
            "scatters": self.scatters,
            "scatter_rows_total": self.scatter_rows_total,
            "windows": self.windows,
        }
