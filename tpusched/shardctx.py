"""Explicit sharding constraints for kernel interiors (ROADMAP item 1,
"make multichip real").

WHY THIS EXISTS. The kernels merge the replicated running-pod tables
with the 'p'-sharded pending-pod tables (`members = [running | pending]`
concatenations in kernels/pairwise.py and the dirty-member refresh in
kernels/assign.py). On a TRUE 2D mesh — both 'p' and 'n' axes > 1 —
this jax/jaxlib's SPMD partitioner materializes such mixed-sharding
concatenates with wrong element routing: a minimal
`jnp.concatenate([replicated, PS('p')-sharded])` under a (2, 4) mesh
returns permuted garbage, while the same program under any 1D mesh is
bit-exact. An explicit `with_sharding_constraint` on the result (pinning
it replicated) removes the partitioner's freedom to pick the broken
layout and restores bitwise parity with the single-device program —
verified by tests/test_mesh.py across (8,1)/(4,2)/(2,4)/(1,8).

Pinning the member-merge results REPLICATED is also the semantically
right layout: every device needs every member column for signature
matching (the [S, M+P] contraction), and the member axis is small next
to the [P, N] tableaux that carry the real memory weight.

MECHANISM. The mesh is threaded EXPLICITLY (`mesh=None` kwargs) from
Engine/solve_core down through the precompute/pairwise helpers to each
merge site; `constrain_replicated(x, mesh)` is the identity for
mesh=None or a 1-device mesh, so single-device traces are byte-for-byte
the programs they were before this module existed.

WHY EXPLICIT AND NOT AMBIENT. jax caches the traced jaxpr per
(function identity, avals) — input SHARDINGS only enter at lowering.
An ambient-context constraint (contextvar read at trace time) therefore
silently vanishes whenever the same function object was first traced
without the mesh at the same shapes: the constraint-free jaxpr is
reused and only re-lowered (observed: the reference solve traced first,
the sharded call reused its jaxpr, the divergence stayed). With the
mesh as an explicit argument, callers close over it per mesh (Engine:
per-instance closures over a construction-fixed self.mesh; tests: a
fresh closure per mesh), so different meshes are different function
identities and can never share a trace.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS


def _active(mesh: Mesh | None) -> Mesh | None:
    if mesh is None or mesh.devices.size <= 1:
        return None
    return mesh


def constrain_replicated(x, mesh: Mesh | None):
    """Pin `x` fully replicated under `mesh`; identity when mesh is None
    or single-device. Apply to every merge of replicated running-member
    data with 'p'-sharded pending-pod data — the op class the 2D-mesh
    partitioner mis-routes (module docstring)."""
    m = _active(mesh)
    if m is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(m, PS()))


def constrain_spec(x, mesh: Mesh | None, *axes):
    """Pin `x` to PartitionSpec(*axes) under `mesh`; identity when mesh
    is None or single-device."""
    m = _active(mesh)
    if m is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(m, PS(*axes)))
