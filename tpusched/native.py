"""Loader/wrapper for the native wire decoder (native/fastdecode.cc).

The C++ extension replicates snapshot_from_proto + SnapshotBuilder.build
end to end (same interning, same bucketing, same arrays — fuzz-tested
for exact equality in tests/test_native.py) but runs ~10x faster on
large snapshots, which matters because decode — not the TPU solve — is
the sidecar's serving bottleneck at 10k x 5k (SURVEY.md §7 hard part 6).

Build-on-demand: the .so is compiled with g++ on first use and cached
next to this file (atomic rename; lock-guarded). No pybind11 — plain
CPython C API + numpy headers. Everything degrades gracefully to the
Python decoder when a compiler is unavailable, and codec.decode_snapshot
falls back to the Python path on any native decode error.

Known divergence from Python float() parsing: non-ASCII numerals in
label values (e.g. Arabic-Indic digits) parse via Python but yield NaN
natively — they silently change Gt/Lt matching on such labels only.
ASCII literals, underscores, inf/nan (any case) all match exactly.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import os
import subprocess
import sys
import sysconfig

import numpy as np

from tpusched.config import Buckets, EngineConfig
from tpusched.snapshot import (
    AtomTable,
    ClusterSnapshot,
    NodeArrays,
    PodArrays,
    RunningPodArrays,
    SigTable,
    SnapshotMeta,
)

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native",
                    "fastdecode.cc")
_SO = os.path.join(os.path.dirname(__file__), "_fastdecode.so")

_mod = None
_build_failed: str | None = None
_load_lock = __import__("threading").Lock()


def _build_so() -> None:
    # Compile to a private temp path and os.replace into place: g++
    # writes -o non-atomically, and concurrent first-callers (the
    # sidecar's thread pool, or a server and a bench sharing the
    # checkout) must never dlopen a half-written file.
    tmp = f"{_SO}.build-{os.getpid()}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{sysconfig.get_paths()['include']}",
        f"-I{np.get_include()}",
        _SRC, "-o", tmp,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise RuntimeError(f"native build failed:\n{proc.stderr[-2000:]}")
    os.replace(tmp, _SO)


def _load():
    global _mod, _build_failed
    if _mod is not None:
        return _mod
    with _load_lock:
        if _mod is not None:
            return _mod
        if _build_failed is not None:
            raise RuntimeError(_build_failed)
        try:
            if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
            ):
                _build_so()
            spec = importlib.util.spec_from_file_location("_fastdecode", _SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)  # tpl: disable=TPL003(one-time native-module load; _load_lock exists precisely to serialize this init)
            _mod = mod
            return mod
        except Exception as e:  # remember: retrying every call would be slow
            _build_failed = f"tpusched native decoder unavailable: {e}"
            raise RuntimeError(_build_failed) from e


def available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


def decode_snapshot_bytes(
    raw: bytes,
    config: EngineConfig | None = None,
    buckets: Buckets | None = None,
) -> tuple[ClusterSnapshot, SnapshotMeta]:
    """Native decode of a serialized tpusched.ClusterSnapshot. Exact
    drop-in for codec.snapshot_from_proto(msg.SerializeToString(), ...)."""
    config = config or EngineConfig()
    mod = _load()
    bdict = dataclasses.asdict(buckets) if buckets is not None else None
    d = mod.decode_snapshot(raw, tuple(config.resources), bdict)
    snap = ClusterSnapshot(
        nodes=NodeArrays(
            allocatable=d["node_allocatable"], used=d["node_used"],
            label_pairs=d["node_label_pairs"], label_keys=d["node_label_keys"],
            label_nums=d["node_label_nums"], taint_ids=d["node_taint_ids"],
            domain=d["node_domain"], schedulable=d["node_schedulable"],
            valid=d["node_valid"],
        ),
        pods=PodArrays(
            requests=d["pod_requests"], base_priority=d["pod_base_priority"],
            slo_target=d["pod_slo_target"],
            observed_avail=d["pod_observed_avail"],
            tolerated=d["pod_tolerated"], label_pairs=d["pod_label_pairs"],
            label_keys=d["pod_label_keys"],
            req_term_atoms=d["pod_req_term_atoms"],
            req_term_valid=d["pod_req_term_valid"],
            pref_term_atoms=d["pod_pref_term_atoms"],
            pref_term_valid=d["pod_pref_term_valid"],
            pref_weight=d["pod_pref_weight"],
            ts_key=d["pod_ts_key"], ts_max_skew=d["pod_ts_max_skew"],
            ts_when=d["pod_ts_when"], ts_sel_atoms=d["pod_ts_sel_atoms"],
            ts_sig=d["pod_ts_sig"], ts_valid=d["pod_ts_valid"],
            ia_key=d["pod_ia_key"], ia_sel_atoms=d["pod_ia_sel_atoms"],
            ia_sig=d["pod_ia_sig"], ia_anti=d["pod_ia_anti"],
            ia_required=d["pod_ia_required"], ia_weight=d["pod_ia_weight"],
            ia_valid=d["pod_ia_valid"], group=d["pod_group"],
            namespace=d["pod_namespace"],
            tolerates_unsched=d["pod_tolerates_unsched"],
            valid=d["pod_valid"],
        ),
        running=RunningPodArrays(
            node_idx=d["run_node_idx"], requests=d["run_requests"],
            priority=d["run_priority"], slack=d["run_slack"],
            label_pairs=d["run_label_pairs"], label_keys=d["run_label_keys"],
            anti_sig=d["run_anti_sig"], namespace=d["run_namespace"],
            pdb_group=d["run_pdb_group"], valid=d["run_valid"],
        ),
        atoms=AtomTable(
            key=d["atom_key"], op=d["atom_op"], pairs=d["atom_pairs"],
            num=d["atom_num"], valid=d["atom_valid"],
        ),
        sigs=SigTable(
            key=d["sig_key"], atoms=d["sig_atoms"], ns=d["sig_ns"],
            ns_all=d["sig_ns_all"], valid=d["sig_valid"],
        ),
        taint_effect=d["taint_effect"],
        group_min_member=d["group_min_member"],
        pdb_allowed=d["pdb_allowed"],
    )
    meta = SnapshotMeta(
        node_names=d["node_names"], pod_names=d["pod_names"],
        n_nodes=d["n_nodes"], n_pods=d["n_pods"], n_running=d["n_running"],
        buckets=Buckets(**d["buckets"]),
        group_names=d["group_names"],
        running_names=d["running_names"],
    )
    return snap, meta
