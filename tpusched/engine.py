"""Engine: the solver driver (SURVEY.md §1.3 "Solver driver" layer).

Owns the jitted solve paths and the host<->device boundary: snapshots
come in as numpy pytrees (from SnapshotBuilder or the gRPC decoder),
results come back as numpy. jax.jit's shape-keyed cache handles bucket
changes; EngineConfig is closed over as compile-time constants.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpusched import ledger as ledgering
from tpusched import trace as tracing
from tpusched.config import EngineConfig
from tpusched.faults import NO_FAULTS
from tpusched.kernels import explain as kexplain
from tpusched.kernels.assign import (_PREEMPT_MAX_ROUNDS, INC_AUDIT_LEN,
                                     EXPLAIN_AUCTION_STATS, build_tableau,
                                     finalize_static, refresh_tableau,
                                     score_batch, solve_incremental,
                                     solve_rounds, solve_sequential)
from tpusched.kernels.atoms import atom_sat
from tpusched.kernels.pairwise import member_label_sat_t
from tpusched.mesh import shard_snapshot
from tpusched.ring import ring_sig_counts
from tpusched.shapeclass import (CAUSE_PREWARM, CAUSE_SERVE,
                                 ShapeClassRegistry, incremental_unassignable,
                                 prewarm_records)
from tpusched.shardctx import constrain_replicated
from tpusched.snapshot import ClusterSnapshot, SnapshotBuilder


@dataclasses.dataclass
class SolveResult:
    assignment: np.ndarray     # [P] int32 node index or -1
    chosen_score: np.ndarray   # [P] f32 (-inf where unschedulable)
    final_used: np.ndarray     # [N, R] f32
    order: np.ndarray          # [P] int32 pop order
    # [P] commit key: pods with smaller keys committed strictly earlier
    # (parity: pop-order position; fast: round index). -1 = unplaced.
    commit_key: np.ndarray | None = None
    rounds: int = 0            # commit rounds (fast mode; P for parity)
    # [M] bool: running pods evicted by preemption (cfg.preemption);
    # the host must delete these before binding their preemptors.
    evicted: np.ndarray | None = None
    solve_seconds: float = 0.0
    # Incremental warm solves only (ISSUE 12): the in-kernel validity
    # audit + frontier accounting — keys cap_violations /
    # static_violations / pair_violations / audit_violations (their
    # sum; the validity contract demands 0) / carried / frontier.
    inc_info: "dict | None" = None


@dataclasses.dataclass
class ScoreBatchResult:
    feasible: np.ndarray       # [P, N] bool
    scores: np.ndarray         # [P, N] f32
    solve_seconds: float = 0.0


@dataclasses.dataclass
class ExplainData:
    """Solve-path provenance extras (round 12, decision provenance):
    which gang placements rolled back, and for every evicted running
    pod WHO evicted it and in which commit round. auction_stats is one
    row per fast-mode preemption round (kernels.assign
    EXPLAIN_AUCTION_STATS columns; all-zero rows are untrimmed here —
    tpusched.explain trims when building records)."""

    rolled: np.ndarray         # [P] bool: reverted by gang_rollback
    evictor: np.ndarray        # [M] int32 preemptor pod index (-1)
    evict_round: np.ndarray    # [M] int32 commit-round key (-1)
    auction_stats: np.ndarray  # [rounds_cap, N_STATS] f32


class _OrderedFetchWorker:
    """ONE background fetch thread with strict FIFO order — the
    replacement for the old single-worker ThreadPoolExecutor. Three
    differences that matter for serving:

      * the thread is a DAEMON, so an engine that was never close()d
        cannot wedge interpreter shutdown, and the owning Engine
        registers a GC finalizer that enqueues the shutdown sentinel —
        dropped engines release their thread like the old executor's
        weakref cleanup did;
      * close(wait=True) DRAINS: the shutdown sentinel enqueues behind
        every submitted fetch, so in-flight PendingFetch results
        complete before close returns;
      * submit after close fails loudly instead of queueing into
        nothing.

    Self-healing (ISSUE 3): per-item exceptions relay into the item's
    Future, so the loop itself only dies on something catastrophic
    (interpreter teardown, a corrupted queue item). A dead-but-not-
    closed worker would silently park every later PendingFetch forever;
    submit() detects that state and RESTARTS the thread — the queue
    survives, only the item that killed the loop is lost (its waiter's
    watchdog/timeout converts the loss into an error).
    """

    def __init__(self, name: str = "tpusched-fetch"):
        self._name = name
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._closed = False
        self.restarts = 0

    def submit(self, fn, *args) -> "Future":
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            if self._thread is not None and not self._thread.is_alive():
                # The loop died on an unexpected exception (not via the
                # shutdown sentinel — _closed is False). Respawn it.
                logging.getLogger("tpusched.engine").warning(
                    "fetch worker %s died unexpectedly; restarting",
                    self._name,
                )
                self._thread = None
                self.restarts += 1
            if self._thread is None:
                # Lazy start: idle engines pay nothing, and the lock
                # keeps concurrent first-submits from double-starting.
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
            self._q.put((fut, fn, args))
        return fut

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001 — relay to waiter
                fut.set_exception(e)

    def close(self, wait: bool = True) -> None:
        """Idempotent and safe to race: the first caller enqueues the
        shutdown sentinel; every waiting caller joins the same thread
        (joining a finished thread is a no-op), so concurrent close vs
        in-flight fetch drains exactly once."""
        with self._lock:
            thread = self._thread
            if not self._closed:
                self._closed = True
                if thread is not None:
                    self._q.put(None)  # behind all pending work: a drain
        if wait and thread is not None:
            thread.join()


@dataclasses.dataclass
class PendingFetch:
    """An in-flight device result: the program is dispatched and its
    packed buffer is being fetched on the engine's background fetch
    thread. `result()` joins and decodes. The point of the split —
    SURVEY.md §2.3 PP, lifted out of pipeline.solve_stream so SERVING
    paths get the same overlap — is that between dispatch and join the
    caller's thread is free for CPU work (the next request's decode,
    response scaffolding), while on fetch-driven transports (the axon
    tunnel: execution only runs while a D2H read is in flight) the
    background np.asarray is what actually drives the device."""

    _unpack: Callable[[np.ndarray, float], Any]
    _fut: Any          # Future[(np buffer, completion perf_counter)]
    _t0: float

    def result(self, timeout: float | None = None):
        """Join the fetch. `timeout` (seconds) raises
        concurrent.futures.TimeoutError when the fetch has not landed
        in time — the sidecar's per-dispatch watchdog uses this to
        convert a hung solve into DEADLINE_EXCEEDED instead of wedging
        the handler thread (the fetch itself keeps running on the
        worker and is simply abandoned)."""
        raw, done_t = self._fut.result(timeout)
        return self._unpack(raw, done_t - self._t0)


def _sat_tables(snap: ClusterSnapshot, mesh=None):
    node_sat_t = atom_sat(
        snap.atoms, snap.nodes.label_pairs, snap.nodes.label_keys,
        snap.nodes.label_nums,
    ).T
    member_sat_t = member_label_sat_t(
        snap, lambda lp, lk: atom_sat(snap.atoms, lp, lk, None), mesh
    )
    return node_sat_t, member_sat_t


def solve_core(cfg: EngineConfig, snap: ClusterSnapshot, mesh=None,
               explain: bool = False, static=None, member_sat_t=None):
    """Mode dispatch shared by Engine and tenants.solve_many: returns
    (assigned, chosen, used, order, commit_key, rounds, evicted) in
    either mode (parity synthesizes commit_key from pop order and
    rounds=P). With cfg.ring_counts and a multi-device mesh, the
    initial pairwise domain counts come from the blockwise ring kernel
    (sig blocks rotating over the 'p' axis via ppermute) instead of the
    dense contraction — bit-identical results, O(S/ndev x members/ndev)
    peak memory (SURVEY.md §2.3 SP/CP row).

    explain=True (decision provenance, round 12) appends one trailing
    tuple (rolled, evictor, evict_round, auction_stats) — see
    solve_rounds/solve_sequential. Placements are IDENTICAL either way
    (the provenance arrays are pure observers; test-pinned).

    static: optional precomputed StaticCtx (the warm path — ROADMAP
    item 3): the sat-table + static-mask/score precompute is skipped and
    `member_sat_t` (the tableau's, needed only by the ring-counts init)
    must ride along."""
    if static is None:
        node_sat_t, member_sat_t = _sat_tables(snap, mesh)
    else:
        node_sat_t = None  # precompute skipped; solve paths take static
    init_counts = None
    if cfg.ring_counts and snap.sigs.key.shape[0]:
        P = snap.pods.valid.shape[0]
        init_counts = ring_sig_counts(
            snap, member_sat_t, jnp.full(P, -1, jnp.int32), mesh
        )
    if cfg.mode == "fast":
        return solve_rounds(cfg, snap, node_sat_t, member_sat_t,
                            init_counts=init_counts, explain=explain,
                            static=static, mesh=mesh)
    seq = solve_sequential(cfg, snap, node_sat_t, member_sat_t,
                           init_counts=init_counts, explain=explain,
                           static=static, mesh=mesh)
    if explain:
        a, c, u, o, ev, extras = seq
    else:
        a, c, u, o, ev = seq
    # parity commit key = position in pop order (strictly serial)
    P = a.shape[0]
    rank = jnp.zeros(P, jnp.int32).at[o].set(
        jnp.arange(P, dtype=jnp.int32)
    )
    base = (a, c, u, o, rank, jnp.int32(P), ev)
    return base + ((extras,) if explain else ())


def _pack_solve(out, mesh=None):
    """Flatten a solve_core output tuple into the ONE f32 result buffer
    (layout authority: Engine.unpack). Shared by the plain, warm, and
    cold-refresh packed programs so the packing cannot drift between
    them. Indices are exact in f32 (< 2^24).

    mesh: the pack concatenates 'p'-sharded pod vectors with replicated
    scalars — the mixed-sharding concat class this jax version's 2D-mesh
    partitioner mis-routes (tpusched/shardctx.py) — so on a mesh the
    result is pinned replicated (it is about to be fetched to the host
    wholesale anyway)."""
    assigned, chosen, used, order, commit_key, rounds, ev = out
    return constrain_replicated(jnp.concatenate([
        assigned.astype(jnp.float32), chosen,
        order.astype(jnp.float32), commit_key.astype(jnp.float32),
        used.reshape(-1), ev.astype(jnp.float32),
        rounds.astype(jnp.float32)[None],
    ]), mesh)


# Per-Engine nonce for compile-watcher keys: jit caches are
# per-instance, so a second engine's first solve at a known shape is a
# NEW compile and must count as one (itertools.count is atomic).
_ENGINE_IDS = itertools.count(1)


def _shape_label(args) -> str:
    """Human shape-class label for the compile timeline: the snapshot's
    bucket dims when one is present, else a leaf-count tag."""
    for a in args:
        if isinstance(a, ClusterSnapshot):
            return (f"P{a.pods.valid.shape[0]}"
                    f"xN{a.nodes.valid.shape[0]}"
                    f"xM{a.running.valid.shape[0]}")
    return f"{len(jax.tree.leaves(args))}leaves"


@dataclasses.dataclass
class WarmState:
    """The carried-state handle of the warm path (ROADMAP item 3): one
    lineage's device-resident WarmTableau plus the identity facts that
    decide whether it may be trusted next cycle. Held by the owning
    DeviceSnapshot (device_state.commit_warm) and consumed only by
    Engine.solve_warm_async — reads of `.tableau` anywhere else are the
    stale-tableau hazard tpuschedlint TPL011 guards."""

    tableau: Any       # device kernels.assign.WarmTableau
    lineage: Any       # DeviceSnapshot.warm_lineage token at build time
    shapes: tuple      # snapshot leaf shapes the tableau was traced at
    engine: Any        # the Engine whose programs built the tableau


class Engine:
    def __init__(self, config: EngineConfig | None = None, mesh=None,
                 faults=None):
        """mesh: optional jax.sharding.Mesh for multi-device solves;
        required when config.ring_counts routes the pairwise counts
        through the ring kernel.

        faults: optional tpusched.faults.FaultPlan; the background
        fetch fires site "engine.fetch" per fetched buffer (a delay
        rule there is a hung solve — what the sidecar watchdog hunts)."""
        self.config = config or EngineConfig()
        self.mesh = mesh
        self._faults = faults if faults is not None else NO_FAULTS
        # Span collector for engine.fetch events; None = the process
        # default at emit time (SchedulerService points this at its own
        # collector so fetch spans land in the same ring — and flight
        # dumps — as the handler spans).
        self.tracer = None
        cfg = self.config
        if cfg.mode not in ("parity", "fast"):
            raise ValueError(f"mode={cfg.mode!r}: want 'parity' or 'fast'")
        if cfg.ring_counts and mesh is None:
            raise ValueError(
                "ring_counts=True needs Engine(mesh=...): the ring "
                "rotates sig blocks over the mesh's 'p' axis"
            )
        if cfg.tie_break not in ("first", "seeded"):
            raise NotImplementedError(
                f"tie_break={cfg.tie_break!r}: want 'first' or 'seeded'"
            )

        def _solve(snap: ClusterSnapshot):
            return solve_core(cfg, snap, mesh=mesh)

        def _solve_packed(snap: ClusterSnapshot):
            # One flat f32 output = ONE device->host fetch. The transport
            # (axon tunnel here, gRPC in deployment) pays a fixed round
            # trip per fetched buffer, which dwarfs the payload cost —
            # same lesson as SURVEY.md §7 hard part 6.
            return _pack_solve(_solve(snap), mesh)

        def _score(snap: ClusterSnapshot):
            node_sat_t, member_sat_t = _sat_tables(snap, mesh)
            ic = None
            if cfg.ring_counts and snap.sigs.key.shape[0]:
                ic = ring_sig_counts(
                    snap, member_sat_t,
                    jnp.full(snap.pods.valid.shape[0], -1, jnp.int32),
                    mesh,
                )
            return score_batch(cfg, snap, node_sat_t, member_sat_t,
                               init_counts=ic, mesh=mesh)

        def _score_top1(snap: ClusterSnapshot):
            feasible, scores = _score(snap)
            masked = jnp.where(feasible, scores, -jnp.inf)
            best = jnp.argmax(masked, axis=1).astype(jnp.int32)
            any_feasible = jnp.any(feasible, axis=1)
            best = jnp.where(any_feasible, best, -1)
            return jnp.stack([
                best.astype(jnp.float32), jnp.max(masked, axis=1),
                any_feasible.astype(jnp.float32),
            ])

        # Compile attribution (round 18, ISSUE 13): every jit entry
        # point is wrapped so the first dispatch of a new shape class
        # records one compile event (count + wall time) in
        # ledger.COMPILES — the per-cycle retrace visibility the cycle
        # ledger's sentinel keys "compile" anomalies off.
        self._jit_nonce = next(_ENGINE_IDS)
        # Shape-class registry hook (ROADMAP item 3): prewarm() fills
        # `families` with the registered family set and dispatch then
        # counts (and warns on) any family traced OUTSIDE it; `cause`
        # labels compile events for the ledger ("prewarm" during boot
        # tracing, "serve" otherwise). A plain dict — NOT self — so the
        # dispatch closures hold no strong ref to the engine (the fetch
        # worker's GC finalizer relies on that).
        self._prewarm_meta: dict[str, Any] = {
            "families": None, "unregistered": {}, "cause": CAUSE_SERVE,
        }
        self.registry: ShapeClassRegistry | None = None
        self._solve_jit = self._traced_jit("solve", _solve)
        self._solve_packed_jit = self._traced_jit("solve_packed",
                                                  _solve_packed)
        self._score_jit = self._traced_jit("score", _score)
        self._score_top1_jit = self._traced_jit("score_top1", _score_top1)
        self._score_fn = _score
        self._topk_jits: dict[int, Any] = {}  # k -> jitted top-k path
        # Decision-provenance programs (round 12): compiled LAZILY on
        # the first solve_explained call, so engines that never explain
        # pay neither trace time nor executable memory for them.
        self._explain_solve_jit = None
        self._explain_probe_jits: dict[int, Any] = {}
        # Warm-start programs (ROADMAP item 3): compiled lazily on the
        # first solve_warm_async call. ONE jit each — jax's shape-keyed
        # cache specializes per (snapshot buckets, pow2-padded dirty
        # sizes, perm presence), and the dirty sizes are pow2-bucketed
        # so the compile set stays bounded.
        self._warm_solve_jit = None
        self._cold_refresh_jit = None
        # Incremental (bounded-divergence) warm programs (ISSUE 12):
        # one jit per FRONTIER BUCKET — the commit rounds run on a
        # [cap, N] compacted view whose width is a compile-time
        # constant, so the frontier size pow2-buckets into a small
        # family exactly like the dirty-row scatters.
        self._warm_inc_jits: dict[int, Any] = {}
        # ONE background fetch worker: fetch order == dispatch order,
        # which fetch-driven transports (axon tunnel) rely on — two
        # concurrent D2H reads would race for the single execution
        # stream. Callers overlap by dispatching the next program while
        # the worker's np.asarray drives the current one. The finalizer
        # restores the old executor's exit-on-GC: an engine dropped
        # WITHOUT close() enqueues the shutdown sentinel when collected,
        # so its (daemon) thread parks forever in neither case. The
        # finalizer must hold the QUEUE, not the worker or self — a
        # strong ref to either would keep the engine alive.
        self._pool_lock = threading.Lock()  # pool swap vs close vs submit
        self._closing = False               # close() wins over restarts
        self._fetch_pool = _OrderedFetchWorker()
        self._pool_finalizer = weakref.finalize(
            self, self._fetch_pool._q.put, None
        )

    def _traced_jit(self, name: str, fn):
        """jax.jit plus compile/retrace attribution (round 18, ISSUE
        13): the FIRST dispatch of a new (engine, program, arg-shapes)
        class runs trace+lower+compile synchronously, so its wall time
        prices the compile; ledger.COMPILES records one event per
        class and cycle emitters diff its counters around a cycle.
        Steady state costs one set-membership check per dispatch (a
        disabled watcher: one attribute read)."""
        jf = jax.jit(fn)  # tpl: disable=TPL103(the _traced_jit factory IS the cache: every call site stores the wrapper in an attr or bounded memo family, which TPL103/TPL104 enforce at those sites)
        nonce = self._jit_nonce
        meta = self._prewarm_meta  # no self capture (see __init__)

        def dispatch(*args):
            watcher = ledgering.COMPILES
            if not watcher.enabled:
                return jf(*args)
            key = (nonce, name,
                   tuple(np.shape(l) for l in jax.tree.leaves(args)))
            if watcher.known(key):
                return jf(*args)
            t0 = time.perf_counter()
            out = jf(*args)
            watcher.note(key, name, _shape_label(args),
                         time.perf_counter() - t0, cause=meta["cause"])
            fams = meta["families"]
            if fams is not None and name not in fams:
                # Registry strictness (counted, not fatal): a family the
                # registry missed still serves — but a prewarmed server
                # was promised zero request-path traces, so the miss is
                # loud and countable (Engine.unregistered_compiles).
                meta["unregistered"][name] = (
                    meta["unregistered"].get(name, 0) + 1)
                logging.getLogger("tpusched.engine").warning(
                    "jit family %r (%s) traced outside the attached "
                    "shape-class registry — add it to "
                    "shapeclass.build_registry so prewarm covers it",
                    name, _shape_label(args),
                )
            return out

        return dispatch

    # -- public API ---------------------------------------------------------

    @staticmethod
    def unpack(snap: ClusterSnapshot, buf) -> SolveResult:
        """Decode _solve_packed's flat buffer (the single authority on
        its layout — solve() and pipeline.solve_stream both go through
        here, so the packing can't drift between them)."""
        buf = np.asarray(buf)
        P = snap.pods.valid.shape[0]
        N, R = snap.nodes.used.shape
        M = snap.running.valid.shape[0]
        base = 4 * P + N * R
        return SolveResult(
            assignment=buf[:P].astype(np.int32),
            chosen_score=buf[P : 2 * P],
            order=buf[2 * P : 3 * P].astype(np.int32),
            commit_key=buf[3 * P : 4 * P].astype(np.int32),
            final_used=buf[4 * P : base].reshape(N, R),
            evicted=buf[base : base + M] > 0,
            rounds=int(buf[-1]),
        )

    def _pool(self) -> _OrderedFetchWorker:
        with self._pool_lock:
            return self._fetch_pool

    def restart_fetch_worker(self) -> None:
        """Abandon a wedged fetch worker (ISSUE 3 watchdog): a fresh
        worker takes all NEW fetches; the old one keeps draining its
        own queue if it ever unwedges (its in-flight futures still
        complete), and its daemon thread can't block shutdown either
        way. Tradeoff, documented: across the swap, fetch order ==
        dispatch order no longer holds between old and new queues — on
        fetch-driven transports two D2H reads may briefly race. A
        worker hung past the watchdog means the device stream is
        already suspect; the ladder quarantines the fast path and this
        swap buys back availability. A no-op once close() has begun:
        swapping a fresh (never-closed) worker in behind a concurrent
        close would void close's drain guarantee and leak the thread."""
        with self._pool_lock:
            if self._closing:
                return
            old = self._fetch_pool
            self._fetch_pool = _OrderedFetchWorker()
            # Detach the abandoned pool's finalizer (its sentinel is
            # enqueued explicitly below): finalizers must not pile up
            # one-per-restart on a persistently wedged device — each
            # would pin a dead worker's queue for the engine's life.
            self._pool_finalizer.detach()
            self._pool_finalizer = weakref.finalize(
                self, self._fetch_pool._q.put, None
            )
        old.close(wait=False)

    def _fetch(self, buf, tctx=None):
        # Completion time measured INSIDE the worker so solve_seconds
        # covers dispatch->fetch-done, not whatever CPU work the caller
        # overlapped with the wait. np.asarray releases the GIL inside
        # the transport wait and, on fetch-driven transports, is what
        # actually runs the program. tree.map: score_async fetches a
        # (feasible, scores) pair through the same worker.
        # tctx: the dispatching request's trace context (captured on
        # the caller's thread at dispatch time — thread-locals don't
        # cross into the worker); the fetch records one span against
        # it, so the stitched trace shows the device window alongside
        # the handler's fetch.join wait.
        self._faults.fire("engine.fetch")
        t0 = time.perf_counter()
        out = jax.tree.map(np.asarray, buf)
        done = time.perf_counter()
        (self.tracer or tracing.DEFAULT).record(
            "engine.fetch", dur_s=done - t0, cat="engine", ctx=tctx)
        return out, done

    def _submit_fetch(self, buf):
        """Queue the D2H fetch, carrying the caller's trace context."""
        tr = self.tracer or tracing.DEFAULT
        return self._pool().submit(self._fetch, buf, tr.current())

    def solve(self, snap: ClusterSnapshot) -> SolveResult:
        """Full batched scheduling: assign every pending pod (or -1).

        Timing includes the device->host readback: on some backends
        (axon tunnel) block_until_ready does not actually block, and the
        host shim needs the assignments anyway — the D2H copy is part of
        the schedule cycle."""
        t0 = time.perf_counter()
        out = self.unpack(snap, self._solve_packed_jit(snap))
        out.solve_seconds = time.perf_counter() - t0
        return out

    def solve_async(self, snap: ClusterSnapshot) -> PendingFetch:
        """Dispatch the packed solve and fetch its one flat buffer on
        the engine's background worker; `.result()` joins and unpacks.
        The caller's thread is free between dispatch and join — the
        decode<->solve overlap primitive behind pipeline.solve_stream
        and the sidecar's staged request handling (in-request overlap:
        response scaffolding builds while the device runs; cross-
        request: the next request's decode overlaps this solve)."""
        t0 = time.perf_counter()
        buf = self._solve_packed_jit(snap)  # async dispatch

        def unpack(raw, seconds):
            res = self.unpack(snap, raw)
            res.solve_seconds = seconds
            return res

        return PendingFetch(unpack, self._submit_fetch(buf), t0)

    # -- O(churn) warm-start solving (ROADMAP item 3) -----------------------

    @staticmethod
    def _pad_idx(idx) -> "np.ndarray | None":
        """Pow2-pad a dirty index list by repeating the first index
        (duplicate scatter writes carry identical recomputed content, so
        order cannot matter) — bounded jit-shape set. None when empty,
        so an all-clean axis skips its scatter at trace time."""
        if idx is None or len(idx) == 0:
            return None
        n = len(idx)
        cap = 1 << max(n - 1, 0).bit_length() if n > 1 else 1
        out = np.full(cap, idx[0], np.int32)
        out[:n] = list(idx)
        return out

    @staticmethod
    def _shape_key(snap: ClusterSnapshot) -> tuple:
        return tuple(np.shape(leaf) for leaf in jax.tree.leaves(snap))

    def _ensure_warm_jits(self) -> None:
        if self._warm_solve_jit is not None:
            return
        cfg, mesh = self.config, self.mesh

        def _cold(snap: ClusterSnapshot):
            node_sat_t, member_sat_t = _sat_tables(snap, mesh)
            tab = build_tableau(cfg, snap, node_sat_t, member_sat_t, mesh)
            static = finalize_static(cfg, snap, tab)
            out = solve_core(cfg, snap, mesh=mesh, static=static,
                             member_sat_t=tab.member_sat_t)
            return _pack_solve(out, mesh), tab

        def _warm(snap: ClusterSnapshot, tab, dp, dn, dm, pperm, nperm,
                  mperm):
            tab = refresh_tableau(cfg, snap, tab, dirty_pods=dp,
                                  dirty_nodes=dn, dirty_members=dm,
                                  pod_perm=pperm, node_perm=nperm,
                                  member_perm=mperm, mesh=mesh)
            static = finalize_static(cfg, snap, tab)
            out = solve_core(cfg, snap, mesh=mesh, static=static,
                             member_sat_t=tab.member_sat_t)
            return _pack_solve(out, mesh), tab

        self._cold_refresh_jit = self._traced_jit("warm_cold_refresh",
                                                  _cold)
        self._warm_solve_jit = self._traced_jit("warm_refresh", _warm)

    def _warm_inc_fn(self, cap: int):
        """The incremental warm program at one frontier-compaction
        bucket (compile-time constant; see _warm_inc_jits)."""
        fn = self._warm_inc_jits.get(cap)
        if fn is None:
            cfg, mesh = self.config, self.mesh

            def _inc(snap: ClusterSnapshot, tab, dp, dn, dm, pperm,
                     nperm, mperm, carry, carry_chosen, frontier, dnode,
                     _cap=cap):
                tab = refresh_tableau(cfg, snap, tab, dirty_pods=dp,
                                      dirty_nodes=dn, dirty_members=dm,
                                      pod_perm=pperm, node_perm=nperm,
                                      member_perm=mperm, mesh=mesh)
                out = solve_incremental(cfg, snap, tab, carry,
                                        carry_chosen, frontier, dnode,
                                        _cap, mesh=mesh)
                return constrain_replicated(jnp.concatenate(
                    [_pack_solve(out[:7], mesh), out[7]]), mesh), tab

            fn = self._warm_inc_jits[cap] = self._traced_jit(
                f"warm_incremental_cap{cap}", _inc)
        return fn

    @staticmethod
    def _k_bucket(k: int, n: int) -> int:
        """Pow2 compile bucket for a top-k request (TPL104, ISSUE 14):
        the top-k jit families are keyed by THIS (O(log N) programs,
        not one per distinct k) and callers slice the first k columns
        — lax.top_k sorts descending, so top-kb's k-prefix IS top-k,
        bitwise. Clamped to n: a bucket past the node axis would pad
        the program for columns that cannot exist."""
        kb = 1 << (max(int(k), 1) - 1).bit_length()
        return min(kb, int(n))

    @staticmethod
    def _frontier_bucket(est: int, P: int) -> int:
        """Pow2 frontier-compaction width for an estimated frontier of
        `est` pods: 2x headroom for closure expansion + revalidation
        spills, floored at 64 (tiny views re-gather more rounds than
        they save), 0 (= full-width rounds) once the bucket would reach
        the pod axis anyway."""
        want = max(64, 2 * max(est, 1))
        cap = 1 << (want - 1).bit_length()
        return 0 if cap >= P else cap

    def unpack_incremental(self, snap: ClusterSnapshot, buf):
        """Decode the incremental program's packed buffer: the standard
        solve layout + the INC_AUDIT_LEN in-kernel audit tail. Returns
        (SolveResult, info dict) — info keys mirror
        SolveResult.inc_info."""
        buf = np.asarray(buf)
        res = Engine.unpack(snap, buf[:-INC_AUDIT_LEN])
        audit = buf[-INC_AUDIT_LEN:]
        info = dict(
            cap_violations=int(audit[0]),
            static_violations=int(audit[1]),
            pair_violations=int(audit[2]),
            audit_violations=int(audit[0] + audit[1] + audit[2]),
            carried=int(audit[3]),
            frontier=int(audit[4]),
        )
        return res, info

    def solve_warm_async(self, device, incremental: bool = False,
                         ) -> PendingFetch:
        """Warm-start solve of a device-resident lineage (ROADMAP item
        3): `device` is a tpusched.device_state.DeviceSnapshot. The
        lineage's accumulated dirty state (device.warm_delta()) decides
        the path:

          * warm — the carried tableau is reordered + scatter-refreshed
            for exactly the dirty pod rows / node columns / member
            columns, then the normal solve runs against it. Per-pod QoS
            weights, score normalizations, pop order, and all pair-state
            counts are recomputed fresh from the CURRENT snapshot every
            solve, so placements are bitwise-identical to a cold solve
            (the twin-parity contract, pinned in tests/test_warm.py).
          * cold — anything the row model cannot express (vocab/bucket
            growth, a rebuild, a foreign or missing tableau) rebuilds
            the tableau from scratch inside the same program; cost is
            the plain solve's, and the lineage is warm again afterwards.

        The handle is committed back into the DeviceSnapshot
        (commit_warm) at DISPATCH time; a caller whose fetch later
        fails should device.invalidate_warm() — the conservative reset.
        Explain mode is not traced on the warm program; use the
        explained (cold) path when provenance is on.

        incremental=True (ISSUE 12, bounded-divergence warm rounds):
        the previous cycle's assignment — committed back into the
        lineage by every solve_warm fetch (DeviceSnapshot.commit_carry)
        — seeds the round loop for clean pods; the dirty set expands to
        its signature-cluster/node closure, carried placements are
        revalidated in batched passes (violations spill), and commit
        rounds run only over the pending frontier on a pow2-bucketed
        compacted view (kernels.assign.solve_incremental). NOT bitwise
        vs cold: governed by the validity contract enforced in-kernel
        (SolveResult.inc_info carries the audit; `python -m
        tpusched.divergence --warm-audit N --incremental` twin-audits
        validity AND placement-quality drift). Falls back to the
        bitwise warm path when the lineage has no carry yet, and to
        cold for everything the row model cannot express — exactly the
        ladder of the plain warm path."""
        self._ensure_warm_jits()
        if incremental and self.config.ring_counts:
            raise NotImplementedError(
                "incremental warm solve does not support ring_counts"
            )
        snap = device.snap
        delta = device.warm_delta()
        warm = device.warm_state
        shapes = self._shape_key(snap)
        reason = None
        if delta.needs_cold:
            reason = delta.reason or "needs_cold"
        elif warm is None:
            reason = "no_tableau"
        elif warm.lineage is not device.warm_lineage:
            # A handle carried across a failover/restore to a DIFFERENT
            # lineage (e.g. a promoted replica) must never be trusted.
            reason = "lineage_mismatch"
        elif warm.engine is not self:
            reason = "engine_mismatch"
        elif warm.shapes != shapes:
            reason = "shape_change"
        carry = device.carry_arrays() if incremental else None
        t0 = time.perf_counter()
        inc_run = False
        if reason is not None:
            buf, tab = self._cold_refresh_jit(snap)
            path, rows = "cold", (0, 0, 0)
        elif incremental and carry is not None:
            carry_arr, chosen_arr = carry
            P = snap.pods.valid.shape[0]
            frontier = np.zeros(P, bool)
            if delta.dirty_pods:
                frontier[np.asarray(delta.dirty_pods, np.int32)] = True
            dnode = None
            if delta.dirty_nodes:
                dnode = np.zeros(snap.nodes.valid.shape[0], bool)
                dnode[np.asarray(delta.dirty_nodes, np.int32)] = True
            # Estimate over REAL rows only (name-sorted reals precede
            # the bucket's padding tail): pad rows read as carry -1 and
            # would inflate the estimate past the pow2 boundary,
            # silently disabling compaction for lineages sitting just
            # above one.
            n_real = len(device.meta.pod_names)
            est = (int(frontier[:n_real].sum())
                   + int((carry_arr[:n_real] < 0).sum()))
            cap = self._frontier_bucket(est, P)
            buf, tab = self._warm_inc_fn(cap)(
                snap, warm.tableau,
                self._pad_idx(delta.dirty_pods),
                self._pad_idx(delta.dirty_nodes),
                self._pad_idx(delta.dirty_members),
                delta.pod_perm, delta.node_perm, delta.member_perm,
                carry_arr, chosen_arr, frontier, dnode,
            )
            path = "incremental"
            inc_run = True
            rows = (len(delta.dirty_pods or ()),
                    len(delta.dirty_nodes or ()),
                    len(delta.dirty_members or ()))
        else:
            buf, tab = self._warm_solve_jit(
                snap, warm.tableau,
                self._pad_idx(delta.dirty_pods),
                self._pad_idx(delta.dirty_nodes),
                self._pad_idx(delta.dirty_members),
                delta.pod_perm, delta.node_perm, delta.member_perm,
            )
            path = "warm"
            rows = (len(delta.dirty_pods or ()),
                    len(delta.dirty_nodes or ()),
                    len(delta.dirty_members or ()))
        device.commit_warm(
            WarmState(tableau=tab, lineage=device.warm_lineage,
                      shapes=shapes, engine=self),
            path=path, reason=reason or "", rows=rows,
        )
        # Name orders of the snapshot THIS dispatch solves, captured
        # now: the carry maps by name, so a concurrent next-cycle
        # apply() shifting rows cannot corrupt it.
        pod_names = list(device.meta.pod_names)
        node_names = list(device.meta.node_names)

        def unpack(raw, seconds):
            if inc_run:
                res, info = self.unpack_incremental(snap, raw)
                res.inc_info = info
            else:
                res = self.unpack(snap, raw)
            res.solve_seconds = seconds
            # Every warm-path result becomes the next incremental
            # cycle's carry (join-thread call — same single-caller
            # discipline as DeviceSnapshot.apply).
            device.commit_carry(pod_names, node_names, res.assignment,
                                np.asarray(res.chosen_score))
            return res

        return PendingFetch(unpack, self._submit_fetch(buf), t0)

    def solve_warm(self, device, incremental: bool = False) -> SolveResult:
        """Blocking form of solve_warm_async."""
        return self.solve_warm_async(device, incremental=incremental).result()

    # -- decision provenance (round 12) -------------------------------------

    def unpack_explained(self, snap: ClusterSnapshot, buf):
        """Decode the explained solve's packed buffer: the standard
        solve layout (Engine.unpack) followed by the provenance extras.
        Returns (SolveResult, ExplainData)."""
        buf = np.asarray(buf)
        P = snap.pods.valid.shape[0]
        N, R = snap.nodes.used.shape
        M = snap.running.valid.shape[0]
        std = 4 * P + N * R + M + 1
        res = Engine.unpack(snap, buf[:std])
        off = std
        rolled = buf[off:off + P] > 0
        off += P
        evictor = buf[off:off + M].astype(np.int32)
        off += M
        evict_round = buf[off:off + M].astype(np.int32)
        off += M
        astats = buf[off:].reshape(
            _PREEMPT_MAX_ROUNDS, len(EXPLAIN_AUCTION_STATS)
        )
        return res, ExplainData(rolled=rolled, evictor=evictor,
                                evict_round=evict_round,
                                auction_stats=astats)

    def solve_explained_async(self, snap: ClusterSnapshot, k: int = 3):
        """Dispatch the EXPLAINED solve plus the provenance probe
        (kernels.explain.explain_probe): returns (pending_solve,
        pending_probe) where the first joins to (SolveResult,
        ExplainData) and the second to a ScoreExplain. Both fetch
        through the engine's ordered worker — no handler-thread D2H.
        Placements are identical to solve(): the explain program only
        ADDS observer arrays (test-pinned). Compiled lazily per shape;
        the unexplained hot path never traces it."""
        cfg = self.config
        mesh = self.mesh
        if self._explain_solve_jit is None:
            def _packed_explained(s: ClusterSnapshot):
                out = solve_core(cfg, s, mesh=mesh, explain=True)
                a, c, u, o, ck, rounds, ev = out[:7]
                rolled, evictor, evict_rd, astats = out[7]
                return jnp.concatenate([
                    a.astype(jnp.float32), c, o.astype(jnp.float32),
                    ck.astype(jnp.float32), u.reshape(-1),
                    ev.astype(jnp.float32),
                    rounds.astype(jnp.float32)[None],
                    rolled.astype(jnp.float32),
                    evictor.astype(jnp.float32),
                    evict_rd.astype(jnp.float32),
                    astats.reshape(-1),
                ])

            self._explain_solve_jit = self._traced_jit(
                "solve_explained", _packed_explained)
        N = snap.nodes.valid.shape[0]
        kk = int(min(max(int(k), 1), max(N, 1)))
        # Compile bucket (TPL104): probe programs are keyed by the pow2
        # bucket of k and unpack slices back — same prefix-stability
        # argument as score_topk_async (lax.top_k sorts descending).
        kb = self._k_bucket(kk, max(N, 1))
        probe_fn = self._explain_probe_jits.get(kb)
        if probe_fn is None:
            def _probe(s: ClusterSnapshot, _k=kb):
                node_sat_t, member_sat_t = _sat_tables(s, mesh)
                ic = None
                if cfg.ring_counts and s.sigs.key.shape[0]:
                    ic = ring_sig_counts(
                        s, member_sat_t,
                        jnp.full(s.pods.valid.shape[0], -1, jnp.int32),
                        mesh,
                    )
                return kexplain.explain_probe(
                    cfg, s, node_sat_t, member_sat_t, _k, init_counts=ic,
                    mesh=mesh,
                )

            probe_fn = self._explain_probe_jits[kb] = self._traced_jit(
                f"explain_probe_k{kb}", _probe)

        t0 = time.perf_counter()
        solve_buf = self._explain_solve_jit(snap)   # async dispatch
        probe_buf = probe_fn(snap)                  # async dispatch

        def unpack_solve(raw, seconds):
            res, exd = self.unpack_explained(snap, raw)
            res.solve_seconds = seconds
            return res, exd

        def unpack_probe(raw, _seconds):
            se = kexplain.unpack_probe(snap, raw, kb)
            if kb == kk:
                return se
            return dataclasses.replace(
                se, k=kk, topk_idx=se.topk_idx[:, :kk],
                topk_score=se.topk_score[:, :kk],
                topk_terms=se.topk_terms[:, :kk, :],
            )

        return (
            PendingFetch(unpack_solve, self._submit_fetch(solve_buf), t0),
            PendingFetch(unpack_probe, self._submit_fetch(probe_buf), t0),
        )

    def solve_explained(self, snap: ClusterSnapshot, k: int = 3):
        """Blocking form: (SolveResult, ExplainData, ScoreExplain)."""
        p_solve, p_probe = self.solve_explained_async(snap, k)
        res, exd = p_solve.result()
        return res, exd, p_probe.result()

    def score(self, snap: ClusterSnapshot) -> ScoreBatchResult:
        """ScoreBatch: [P, N] feasibility + normalized weighted scores,
        no commits (the Score-plugin backend of the north star)."""
        return self.score_async(snap).result()

    def score_async(self, snap: ClusterSnapshot) -> PendingFetch:
        """Async form of score(): both matrices fetched on the engine's
        ordered fetch worker. Serving handlers must use this (or any
        *_async form) rather than fetching on their own thread — a
        handler-thread np.asarray would race the worker's in-flight
        fetch on fetch-driven transports."""
        def unpack(pair, seconds):
            feasible, scores = pair
            return ScoreBatchResult(
                feasible=feasible, scores=scores, solve_seconds=seconds
            )

        t0 = time.perf_counter()
        out = self._score_jit(snap)  # async dispatch
        return PendingFetch(unpack, self._submit_fetch(out), t0)

    def score_topk(self, snap: ClusterSnapshot, k: int):
        """Top-k of the ScoreBatch matrix computed ON DEVICE: each
        pod's best k feasible nodes (descending) and their scores,
        fetched as one packed [2*P*k] f32 buffer (node indices are
        exact in f32: N < 2^24). This is the O(P) serving form of the
        Score-plugin surface — the [P, N] matrix never leaves the
        device; upstream's percentageOfNodesToScore likewise narrows
        the scored-node set at scale. Returns (idx[P,k] int32 with -1
        where fewer than k feasible, scores[P,k] f32 with 0 at -1
        slots, seconds)."""
        res = self.score_topk_async(snap, k)
        idx, val, seconds = res.result()
        return idx, val, seconds

    def score_topk_async(self, snap: ClusterSnapshot, k: int) -> PendingFetch:
        """Async form of score_topk (same packed buffer, background
        fetch): `.result()` -> (idx, val, seconds). Lets the sidecar's
        ScoreBatch handler build its response name tables while the
        device ranks."""
        k = int(k)
        N = snap.nodes.valid.shape[0]
        if not 1 <= k <= N:
            raise ValueError(
                f"top_k={k} out of range for {N} node slots"
            )
        # Compile bucket (TPL104): the family is keyed by the pow2
        # bucket, the device ranks kb columns, and unpack slices the
        # first k — identical to a direct top-k (descending sort).
        kb = self._k_bucket(k, N)
        fn = self._topk_jits.get(kb)
        if fn is None:
            score = self._score_fn

            def _topk(s: ClusterSnapshot, _kb=kb):
                feasible, scores = score(s)
                masked = jnp.where(feasible, scores, -jnp.inf)
                v, i = jax.lax.top_k(masked, _kb)
                ok = jnp.isfinite(v)
                return jnp.concatenate([
                    jnp.where(ok, i, -1).astype(jnp.float32).ravel(),
                    jnp.where(ok, v, 0.0).ravel(),
                ])

            fn = self._topk_jits[kb] = self._traced_jit(
                f"score_topk_k{kb}", _topk)
        P = snap.pods.valid.shape[0]

        def unpack(buf, seconds):
            half = P * kb
            idx = buf[:half].astype(np.int32).reshape(P, kb)[:, :k]
            val = buf[half:].reshape(P, kb).astype(np.float32)[:, :k]
            return idx, val, seconds

        t0 = time.perf_counter()
        buf = fn(snap)  # async dispatch
        return PendingFetch(unpack, self._submit_fetch(buf), t0)

    def score_top1(self, snap: ClusterSnapshot):
        """Full [P, N] scoring on device, returning only each pod's best
        node, its score, and feasibility — the decision-ready contract
        the host shim binds on (full matrix stays on device)."""
        t0 = time.perf_counter()
        buf = np.asarray(self._score_top1_jit(snap))
        return (
            buf[0].astype(np.int32), buf[1], buf[2] > 0,
            time.perf_counter() - t0,
        )

    @property
    def unregistered_compiles(self) -> dict[str, int]:
        """Per-family count of compiles traced OUTSIDE the attached
        shape-class registry (empty until prewarm() attaches one).
        Counted + warned, never fatal — the miss list is the work item
        for shapeclass.build_registry."""
        return dict(self._prewarm_meta["unregistered"])

    class _PrewarmStop(Exception):
        """Raised between shape classes when a prewarm's should_stop
        callable fires — cooperative cancellation, never an error."""

    def prewarm(self, registry: ShapeClassRegistry,
                should_stop=None) -> dict:
        """Trace every shape class in `registry` (ROADMAP item 3): after
        this returns, a request at the registry's buckets through any
        registered family dispatches an already-compiled program — the
        compile-free failover a promoted standby needs. Also ATTACHES
        the registry: later compiles outside its family set are counted
        in `unregistered_compiles` and logged (not fatal).

        Leaf shapes are a pure function of explicit Buckets, so the tiny
        canonical clusters from shapeclass.prewarm_records compile the
        exact programs real traffic at those buckets hits. Warm families
        are driven through a real DeviceSnapshot lineage with the
        canonical smallest delta (one upserted pod); the incremental
        family needs one lineage per frontier cap, steered by
        unassignable filler pods (shapeclass.incremental_unassignable).

        Compile events recorded during this call carry cause="prewarm"
        in ledger.COMPILES so boot work never reads as a serving
        regression. Returns a report dict (classes / families /
        compiles / compile_s / prewarm_s / cancelled).

        should_stop: optional zero-arg callable polled BETWEEN shape
        classes; returning True abandons the remaining classes (the
        report comes back cancelled=True). A closing server uses this
        so a boot prewarm racing shutdown stops after the in-flight
        compile instead of keeping a thread inside XLA while the
        interpreter tears down."""
        from tpusched.device_state import DeviceSnapshot  # tpl: disable=TPL001(boot-time only: prewarm runs once per process; a top-level import would tax every engine import with the device-state layer it otherwise never needs)

        t0 = time.perf_counter()
        bk = registry.buckets
        fams = frozenset(registry.families())
        self.registry = registry
        self._prewarm_meta["families"] = fams
        before = ledgering.COMPILES.counters()
        prev_cause = self._prewarm_meta["cause"]
        self._prewarm_meta["cause"] = CAUSE_PREWARM
        cancelled = False

        def _ck() -> None:
            if should_stop is not None and should_stop():
                raise Engine._PrewarmStop

        try:
            nodes, pods, running = prewarm_records(self.config)
            b = SnapshotBuilder(self.config, buckets=bk)
            for n in nodes:
                b.add_node(**n)
            for p in pods:
                b.add_pod(**p)
            for r in running:
                b.add_running_pod(**{k: v for k, v in r.items()
                                     if k != "name"})
            snap, _meta = b.build()
            snap = self.put(snap)
            if "solve_packed" in fams:
                _ck()
                self.solve_async(snap).result()
            if "score" in fams:
                _ck()
                self.score_async(snap).result()
            if "score_top1" in fams:
                _ck()
                self.score_top1(snap)
            for cls in registry:
                if cls.family.startswith("score_topk_k"):
                    _ck()
                    self.score_topk_async(
                        snap, dict(cls.params)["k"]).result()
            if registry.explain:
                _ck()
                p_solve, p_probe = self.solve_explained_async(
                    snap, registry.explain_k)
                p_solve.result()
                p_probe.result()
            if registry.warm is not None:
                caps = ([dict(c.params)["cap"] for c in registry
                         if c.family.startswith("warm_incremental_cap")]
                        if registry.warm == "incremental" else [None])
                for cap in caps:
                    _ck()
                    filler = (0 if cap is None else
                              incremental_unassignable(cap, bk.pods))
                    wn, wp, wr = prewarm_records(self.config, filler)
                    ds = DeviceSnapshot(self.config, bk, mesh=self.mesh)
                    ds.full_load(wn, wp, wr)
                    self.solve_warm(ds)                # warm_cold_refresh
                    ds.apply(upsert_pods=[wp[0]])
                    self.solve_warm(ds)                # warm_refresh
                    if cap is not None:
                        ds.apply(upsert_pods=[wp[0]])
                        self.solve_warm(ds, incremental=True)
        except Engine._PrewarmStop:
            cancelled = True
        finally:
            self._prewarm_meta["cause"] = prev_cause
        after = ledgering.COMPILES.counters()
        return dict(
            classes=len(registry),
            families=sorted(fams),
            compiles=after[0] - before[0],
            compile_s=round(after[1] - before[1], 6),
            prewarm_s=round(time.perf_counter() - t0, 6),
            cancelled=cancelled,
        )

    def warmup(self, snap: ClusterSnapshot) -> None:
        """Trigger compilation of the serving paths (solve + score_top1)
        for this snapshot's bucket shapes."""
        self._solve_packed_jit(snap)
        self._score_top1_jit(snap)

    def put(self, snap: ClusterSnapshot) -> ClusterSnapshot:
        """Explicit host->device transfer (otherwise implicit on call).
        On a mesh-backed engine the snapshot lands SHARDED in the
        canonical layout (pods over 'p', nodes over 'n', vocab
        replicated) so the solve consumes it in place — one engine
        serves a cluster no single device holds (ROADMAP item 1)."""
        if self.mesh is not None and self.mesh.devices.size > 1:
            return shard_snapshot(self.mesh, snap)
        return jax.device_put(snap)

    def close(self, wait: bool = True) -> None:
        """Shut down the background fetch worker. wait=True (default)
        DRAINS: every in-flight PendingFetch completes before this
        returns, so multi-client servers can't leak fetch threads or
        half-fetched results across test runs. The worker thread is a
        daemon, so engines that are never closed still can't block
        interpreter shutdown. Idempotent, and safe against a concurrent
        close or restart_fetch_worker: `_closing` is set under the pool
        lock BEFORE the current pool is read, so a racing watchdog
        restart either completed its swap (we close the new pool) or
        becomes a no-op — no fresh never-closed worker can appear
        behind us (worker.close is itself re-entrant)."""
        with self._pool_lock:
            self._closing = True
            pool = self._fetch_pool
        pool.close(wait=wait)
