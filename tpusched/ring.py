"""Ring/blockwise pairwise counting (SURVEY.md §2.3 "SP/CP" row, §5
"Long-context analogue").

The domain-count state counts[s, d] = members matching signature s in
domain d is the contraction of a [S, M+P] match matrix against member
placement — this domain's analogue of attention's [Q, K] scores. The
sig-table design already keeps it compact, but at extreme scale (many
signatures × hundreds of thousands of members) the full [S, M+P] match
matrix need not materialize on any single device:

  * member blocks (labels' atom-satisfaction columns, namespaces, node,
    validity) stay RESIDENT, sharded over the 'p' mesh axis;
  * signature blocks (selector atoms, topology key, ns scope) ROTATE
    around the ring via lax.ppermute, each carrying its accumulated
    [S_blk, N] counts with it;
  * after ndev hops every signature block has seen every member block
    and returns home with complete counts.

Structurally identical to ring attention (KV blocks rotating past
resident Q blocks, accumulating output) — compute overlaps the ICI
transfer of the next block, and peak memory per device is
O(S/ndev x members/ndev), never O(S x members).

Numerically identical to kernels/pairwise.sig_counts (integer adds in
f32, order-independent below 2^24).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

try:  # jax >= 0.6: public top-level name, check_vma kwarg
    from jax import shard_map

    # Native shard_map handles meshes with axes the specs don't
    # mention (replication over 'n') correctly.
    SHARD_MAP_2D_MESH_OK = True
except ImportError:  # 0.4.x (this image): experimental namespace,
    # and the replication-check kwarg is spelled check_rep there.
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    # KNOWN LIMITATION of the 0.4.x experimental shard_map: on a mesh
    # with a second ('n') axis > 1 that the specs treat as replicated,
    # the ppermute ring mis-routes and the counts come back wrong
    # (verified empirically: every (ndev, 1) mesh is bit-exact vs the
    # dense path, every (p, n>1) mesh diverges, with or without
    # check_rep). 1D 'p' rings — the layout the ring path exists for —
    # are unaffected; 2D-mesh ring tests skip on this flag.
    SHARD_MAP_2D_MESH_OK = False

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map_exp(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

from tpusched.kernels.atoms import gather_term_sat
from tpusched.kernels.pairwise import ns_scope_ok
from tpusched.mesh import POD_AXIS
from tpusched.snapshot import ClusterSnapshot


def _pad_to(x, mult: int, axis: int, fill):
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad, constant_values=fill)


def ring_sig_counts(
    snap: ClusterSnapshot,
    member_sat_t,
    assigned,
    mesh: Mesh,
):
    """[S, N] f32 domain counts, computed blockwise around the 'p' ring.

    member_sat_t: [A, M+P] atom satisfaction over member labels (from
    pairwise.member_label_sat_t). assigned: [P] int32 committed node per
    pending pod (-1 = not placed). Returns the same counts as
    kernels/pairwise.sig_counts for snapshots whose selectors' AND-lists
    fit the sig atom bucket (always true by construction).
    """
    ndev = mesh.shape[POD_AXIS]
    run, pods, sigs = snap.running, snap.pods, snap.sigs
    N = snap.nodes.valid.shape[0]
    S = sigs.key.shape[0]

    # Member-axis data (resident, sharded over 'p').
    mnode = jnp.concatenate([run.node_idx, assigned])
    mvalid = jnp.concatenate([run.valid, assigned >= 0])
    mns = jnp.concatenate([run.namespace, pods.namespace])
    msat = member_sat_t  # [A, MP]

    # Pad both the member axis and the signature axis to ndev multiples.
    msat = _pad_to(msat, ndev, 1, False)
    mnode = _pad_to(mnode, ndev, 0, -1)
    mvalid = _pad_to(mvalid, ndev, 0, False)
    mns = _pad_to(mns, ndev, 0, -1)
    skey = _pad_to(sigs.key, ndev, 0, -1)
    satoms = _pad_to(sigs.atoms, ndev, 0, -1)
    sns = _pad_to(sigs.ns, ndev, 0, -1)
    snsall = _pad_to(sigs.ns_all, ndev, 0, False)
    svalid = _pad_to(sigs.valid, ndev, 0, False)
    Sp = skey.shape[0]

    # Domain id of node n under topology key k, replicated: [N, TK].
    ndom = snap.nodes.domain

    def kernel(msat, mnode, mvalid, mns, skey, satoms, sns, snsall, svalid):
        # Shapes inside: member arrays hold this device's block
        # ([A, mblk], [mblk], ...); sig arrays hold the CURRENT sig
        # block ([sblk], [sblk, AT], ...), initially this device's own.
        sblk = skey.shape[0]
        perm = [(i, (i + 1) % ndev) for i in range(ndev)]

        def match_block(skey, satoms, sns, snsall, svalid):
            # [sblk, mblk]: same selector-AND + namespace-scope semantics
            # as pairwise.sig_member_match, via the shared kernels.
            match = gather_term_sat(msat, satoms)     # [sblk, mblk]
            ns_ok = ns_scope_ok(sns, snsall, mns)
            return match & ns_ok & svalid[:, None] & mvalid[None, :]

        def body(carry, _):
            skey, satoms, sns, snsall, svalid, counts = carry
            match = match_block(skey, satoms, sns, snsall, svalid)
            # Domain of each member's node under each sig's key.
            if ndom.shape[1]:
                dom_s = ndom[:, jnp.clip(skey, 0, None)].T    # [sblk, N]
                dom_s = jnp.where((skey >= 0)[:, None], dom_s, -1)
            else:
                dom_s = jnp.full((sblk, N), -1, jnp.int32)
            mdom = jnp.where(
                (mnode >= 0)[None, :],
                dom_s[:, jnp.clip(mnode, 0, None)], -1
            )                                                  # [sblk, mblk]
            contrib = (match & (mdom >= 0)).astype(jnp.float32)
            rows = jnp.broadcast_to(
                jnp.arange(sblk)[:, None], mdom.shape
            )
            counts = counts.at[rows, jnp.clip(mdom, 0, None)].add(contrib)
            # Rotate the sig block AND its accumulated counts to the
            # next device; after ndev hops they are home and complete.
            nxt = [
                jax.lax.ppermute(x, POD_AXIS, perm)
                for x in (skey, satoms, sns, snsall, svalid, counts)
            ]
            return tuple(nxt), None

        init = (skey, satoms, sns, snsall, svalid,
                jnp.zeros((sblk, N), jnp.float32))
        (skey, satoms, sns, snsall, svalid, counts), _ = jax.lax.scan(
            body, init, None, length=ndev
        )
        return counts

    p = PS(POD_AXIS)
    counts = shard_map(
        kernel,
        mesh=mesh,
        in_specs=(
            PS(None, POD_AXIS),  # msat: member axis sharded
            p, p, p,             # mnode, mvalid, mns
            p,                   # skey: sig axis sharded
            PS(POD_AXIS, None),  # satoms
            PS(POD_AXIS, None),  # sns
            p, p,                # snsall, svalid
        ),
        out_specs=PS(POD_AXIS, None),
        check_vma=False,
    )(msat, mnode, mvalid, mns, skey, satoms, sns, snsall, svalid)
    return counts[:S]


# ring_sig_counts_host, the old per-call-jit convenience wrapper, was
# DELETED here (round 19, ISSUE 14): it had no callers anywhere in the
# tree and re-jitted (so retraced) on every invocation — the exact
# TPL103 hazard class. Callers wanting a host-side one-shot should go
# through Engine (whose jit families are cached and bounded) or jit
# `ring_sig_counts` themselves at module scope.
