"""The TPU scheduling sidecar (SURVEY.md C12): a gRPC server wrapping
Engine. This is the process a `--score-backend=tpu` scheduler talks to
(BASELINE.json:"north_star").

Service stubs are hand-wired with grpc generic handlers (the image has
protoc + grpcio but no grpc_tools codegen); the method table mirrors
protos/tpusched.proto's service block.

Request handling is STAGED (round 6, SURVEY.md §2.3 PP in-request):
decode runs outside the device dispatch lane (concurrent across
handler threads), dispatch holds the lane just long enough to enqueue
the program (Engine.solve_async / score_topk_async — one ordered
background fetch worker), and the response's name tables build while
the device runs. A single pipelined connection (client
AssignPipeline, depth 2) therefore overlaps request k+1's decode with
request k's solve — the overlap that previously required two
concurrent schedulers — and even a strictly sequential client gets
its response scaffolding for free inside the device window.

Observability (SURVEY.md §5): every batch emits one structured JSON log
line (sizes, rounds, per-phase seconds, placements/sec) on stderr, and
the Metrics rpc serves Prometheus text with upstream-compatible metric
names (scheduler_e2e_scheduling_duration_seconds etc.).
"""

from __future__ import annotations

import json
import sys
import time
from concurrent import futures

import numpy as np

import grpc

from tpusched.config import Buckets, EngineConfig
from tpusched.engine import Engine
from tpusched.rpc import tpusched_pb2 as pb
from tpusched.rpc.codec import SnapshotStore, decode_snapshot, delta_safe

SERVICE = "tpusched.TpuScheduler"

# Recent snapshot stores kept for delta resolution. Each store holds
# references into decoded request protos (cheap); the cap bounds memory
# and defines how stale a client's base_id may be before it must resend
# a full snapshot.
STORE_CAP = 8

# Above this many matrix cells a packed_ok ScoreBatch response switches
# from repeated ScoreRow to the packed-bytes form: the row form costs
# one pure-Python proto setter per cell (5*10^7 floats + bools at
# 10k x 5k — minutes, round-3 verdict missing #2), the packed form two
# ndarray.tobytes() calls.
PACK_CELLS = 1 << 15


class _Metrics:
    """Tiny Prometheus registry: counters + a duration histogram with
    upstream scheduler metric names."""

    BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

    def __init__(self):
        import threading

        self._lock = threading.Lock()  # handlers run on a thread pool
        self.attempts = 0
        self.placements = 0
        self.evictions = 0
        self.batches = 0
        self.hist = [0] * (len(self.BUCKETS) + 1)
        self.dur_sum = 0.0

    def observe(self, n_pods: int, n_placed: int, n_evicted: int, dur: float):
        with self._lock:
            self.attempts += n_pods
            self.placements += n_placed
            self.evictions += n_evicted
            self.batches += 1
            self.dur_sum += dur
            for i, b in enumerate(self.BUCKETS):
                if dur <= b:
                    self.hist[i] += 1
                    break
            else:
                self.hist[-1] += 1

    def render(self) -> str:
        with self._lock:
            return self._render_locked()

    def _render_locked(self) -> str:
        lines = [
            "# TYPE scheduler_schedule_attempts_total counter",
            f"scheduler_schedule_attempts_total {self.attempts}",
            "# TYPE scheduler_pod_placements_total counter",
            f"scheduler_pod_placements_total {self.placements}",
            "# TYPE scheduler_preemption_victims_total counter",
            f"scheduler_preemption_victims_total {self.evictions}",
            "# TYPE scheduler_batches_total counter",
            f"scheduler_batches_total {self.batches}",
            "# TYPE scheduler_e2e_scheduling_duration_seconds histogram",
        ]
        cum = 0
        for b, c in zip(self.BUCKETS, self.hist):
            cum += c
            lines.append(
                f'scheduler_e2e_scheduling_duration_seconds_bucket{{le="{b}"}} {cum}'
            )
        cum += self.hist[-1]
        lines.append(
            f'scheduler_e2e_scheduling_duration_seconds_bucket{{le="+Inf"}} {cum}'
        )
        lines.append(
            f"scheduler_e2e_scheduling_duration_seconds_sum {self.dur_sum:.6f}"
        )
        lines.append(
            f"scheduler_e2e_scheduling_duration_seconds_count {self.batches}"
        )
        return "\n".join(lines) + "\n"


class SchedulerService:
    def __init__(
        self,
        config: EngineConfig | None = None,
        buckets: Buckets | None = None,
        log_stream=None,
        audit_stream=None,
    ):
        """audit_stream: optional file-like; when set, every Assign
        emits one JSON record PER POD (pod, node, score, commit_key —
        the upstream per-pod placement-decision audit, SURVEY.md §5
        'Metrics/observability') plus one per eviction. Off by default:
        at 10k pods a full audit is ~1 MB per batch."""
        self.config = config or EngineConfig()
        # Floor buckets pin compile shapes across requests (a feature
        # first appearing mid-serving would otherwise trigger a full
        # recompile stall; SnapshotBuilder docstring caveat).
        self.buckets = buckets
        self.metrics = _Metrics()
        # A configured mesh shape (or the ring path, which needs a
        # mesh) puts the sidecar's engine on a device mesh — the YAML
        # route to the sharded/ring paths (EngineConfig.mesh_shape).
        mesh = None
        if self.config.ring_counts or tuple(self.config.mesh_shape) != (1, 1):
            from tpusched.mesh import make_mesh

            shape = tuple(self.config.mesh_shape)
            mesh = make_mesh(None if shape == (1, 1) else shape)
        self._engine = Engine(self.config, mesh=mesh)
        self._log = log_stream if log_stream is not None else sys.stderr
        self._audit = audit_stream
        import threading

        self._audit_lock = threading.Lock()  # handlers run on a pool
        self._store_lock = threading.Lock()
        self._stores: dict[str, SnapshotStore] = {}  # LRU by insertion
        self._next_store = 0
        # Device dispatch lane (round 6, in-request decode<->solve
        # overlap): handlers decode OUTSIDE the lane (pure CPU, runs
        # concurrently on the gRPC thread pool), hold the lane only to
        # DISPATCH, then build their response scaffolding while the
        # engine's background worker fetches. Request k+1's decode and
        # dispatch therefore overlap request k's in-flight solve even
        # on a single pipelined connection; the lane plus the engine's
        # single ordered fetch worker keep dispatch order == fetch
        # order, which fetch-driven transports require.
        self._dispatch_lane = threading.Lock()

    def _register_store(self, store: SnapshotStore) -> str:
        with self._store_lock:
            sid = f"snap-{self._next_store}"
            self._next_store += 1
            self._stores[sid] = store
            while len(self._stores) > STORE_CAP:
                self._stores.pop(next(iter(self._stores)))
        return sid

    @staticmethod
    def _check_delta_upserts(delta, context) -> None:
        """Defense-in-depth behind DeltaSession's client-side guard: a
        delta upsert with an empty or duplicate name would silently
        collapse in the name-keyed store and solve a corrupted snapshot.
        Reject loudly instead (INVALID_ARGUMENT — retrying the same
        delta cannot succeed, unlike an expired base)."""
        for coll in (delta.upsert_nodes, delta.upsert_pods,
                     delta.upsert_running):
            seen = set()
            for rec in coll:
                if not rec.name or rec.name in seen:
                    context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "delta upserts must carry unique non-empty names "
                        f"(offending record name: {rec.name!r})",
                    )
                seen.add(rec.name)

    def _resolve(self, request, context):
        """Full-or-delta request -> (ClusterSnapshot msg, snapshot_id).
        Unknown/expired base_id aborts FAILED_PRECONDITION so the client
        falls back to a full snapshot (DeltaSession does). Snapshots
        whose records lack unique non-empty names are served but not
        registered (empty snapshot_id): name-keyed stores would collapse
        them (DeltaSession refuses to delta against those too)."""
        if request.HasField("delta"):
            if not request.delta.base_id:
                # Falling through would silently solve the empty default
                # snapshot; a delta without a base cannot be resolved.
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "delta request carries no base_id",
                )
            self._check_delta_upserts(request.delta, context)
            with self._store_lock:
                base = self._stores.get(request.delta.base_id)
                if base is not None:
                    # True-LRU refresh: a hit keeps the base alive while
                    # unrelated sessions churn the cap.
                    self._stores.pop(request.delta.base_id)
                    self._stores[request.delta.base_id] = base
            if base is None:
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"unknown snapshot base_id {request.delta.base_id!r}",
                )
            store = base.copy()
            store.apply_delta(request.delta)
            # Bytes composition straight into the (native) decoder: no
            # Python ClusterSnapshot is materialized on the delta path.
            return store.compose_bytes(), self._register_store(store)
        msg = request.snapshot
        if not delta_safe(msg):
            return msg, ""
        store = SnapshotStore()
        # One serialize pass per record at full-send time so every
        # later delta cycle serializes only its churn (apply_delta) and
        # composes by concatenation.
        store.set_full_bytes(msg)
        return msg, self._register_store(store)

    def _decode(self, snapshot_msg):
        t0 = time.perf_counter()
        snap, meta = decode_snapshot(
            snapshot_msg, self.config, self.buckets
        )
        return snap, meta, time.perf_counter() - t0

    def _log_batch(self, rpc: str, meta, decode_s: float, solve_s: float,
                   placed: int, evicted: int, rounds: int):
        rec = dict(
            ts=time.time(), rpc=rpc, pods=meta.n_pods, nodes=meta.n_nodes,
            running=meta.n_running, buckets=[meta.buckets.pods, meta.buckets.nodes],
            decode_s=round(decode_s, 6), solve_s=round(solve_s, 6),
            placed=placed, evicted=evicted, rounds=rounds,
            placements_per_sec=round(placed / solve_s, 1) if solve_s > 0 else 0,
        )
        print(json.dumps(rec), file=self._log, flush=True)

    # -- rpc methods --------------------------------------------------------

    def ScoreBatch(self, request: pb.ScoreRequest, context) -> pb.ScoreResponse:
        msg, sid = self._resolve(request, context)
        snap, meta, decode_s = self._decode(msg)
        resp = pb.ScoreResponse(snapshot_id=sid)
        P, N = meta.n_pods, meta.n_nodes
        # Staged (see the lane comment in __init__): dispatch the device
        # work for whichever form was requested, then build the response
        # name tables — ONE authority, below — while the fetch is in
        # flight. Both forms fetch through the engine's ordered worker:
        # a handler-thread fetch would race a pipelined Assign's
        # in-flight fetch on fetch-driven transports.
        pending_topk = pending_full = None
        k = 0
        if request.top_k > 0:
            # O(P) response: top-k computed on device, [P,N] never
            # fetched. The only form that serves the headline shape
            # under budget on bandwidth-limited links. A drained
            # cluster (N == 0) has nothing to rank: k stays 0 with no
            # rows, which the client decodes as [P, 0] arrays.
            if N > 0:
                k = min(int(request.top_k), N)
                with self._dispatch_lane:
                    pending_topk = self._engine.score_topk_async(snap, k)
        else:
            with self._dispatch_lane:
                pending_full = self._engine.score_async(snap)
        resp.pod_names.extend(meta.pod_names)
        resp.node_names.extend(meta.node_names)
        solve_s = 0.0
        if pending_topk is not None:
            idx, val, solve_s = pending_topk.result()
            resp.k = k
            resp.topk_idx_packed = np.ascontiguousarray(
                idx[:P], dtype="<i4"
            ).tobytes()
            resp.topk_score_packed = np.ascontiguousarray(
                val[:P], dtype="<f4"
            ).tobytes()
        elif pending_full is not None:
            res = pending_full.result()
            solve_s = res.solve_seconds
            if request.packed_ok and P * N >= PACK_CELLS:
                resp.feasible_packed = np.ascontiguousarray(
                    res.feasible[:P, :N], dtype=np.uint8
                ).tobytes()
                resp.scores_packed = np.ascontiguousarray(
                    res.scores[:P, :N], dtype="<f4"
                ).tobytes()
            else:
                for i in range(P):
                    row = resp.rows.add()
                    row.feasible.extend(res.feasible[i, :N].tolist())
                    row.scores.extend(res.scores[i, :N].tolist())
        self._log_batch("ScoreBatch", meta, decode_s, solve_s, 0, 0, 0)
        self.metrics.observe(P, 0, 0, decode_s + solve_s)
        return resp

    def Assign(self, request: pb.AssignRequest, context) -> pb.AssignResponse:
        msg, sid = self._resolve(request, context)
        snap, meta, decode_s = self._decode(msg)
        # Staged handling (round 6): decode ran OUTSIDE the lane (so a
        # concurrent request's decode overlaps this solve), dispatch
        # holds the lane only long enough to enqueue the program, and
        # the response's name tables build while the engine's worker
        # drives the device and fetches the packed buffer.
        with self._dispatch_lane:
            pending = self._engine.solve_async(snap)
        resp = pb.AssignResponse(snapshot_id=sid)
        P = meta.n_pods
        if request.packed_ok:
            # Name tables now, result arrays after the join: the two
            # string extends are the response's CPU-heavy part at 10k
            # pods and ride inside the device window for free.
            resp.pod_names.extend(meta.pod_names)
            # Indices resolve against the DECODER's canonical (sorted)
            # node order, not the request's wire order — ship the table.
            resp.node_names.extend(meta.node_names)
        res = pending.result()
        ni = np.asarray(res.assignment[:P], dtype=np.int32)
        sc = np.asarray(res.chosen_score[:P], dtype=np.float32).copy()
        sc[~np.isfinite(sc)] = 0.0  # -inf (unplaced/preempted) -> 0
        ck = np.asarray(res.commit_key[:P], dtype=np.int32)
        placed = int((ni >= 0).sum())
        if request.packed_ok:
            # Parallel-array form: three tobytes() instead of P Python
            # message constructions (~30 ms saved at 10k pods).
            resp.node_idx_packed = ni.astype("<i4").tobytes()
            resp.score_packed = sc.astype("<f4").tobytes()
            resp.commit_key_packed = ck.astype("<i4").tobytes()
        else:
            for i, name in enumerate(meta.pod_names):
                a = resp.assignments.add()
                a.pod = name
                n = int(ni[i])
                if n >= 0:
                    a.node = meta.node_names[n]
                    a.score = float(sc[i])
                a.commit_key = int(ck[i])
        n_evicted = 0
        if res.evicted is not None and res.evicted.any():
            running_names = getattr(meta, "running_names", None) or []
            for m in np.argwhere(res.evicted).ravel():
                if m < len(running_names):
                    resp.evicted.append(running_names[m])
                    n_evicted += 1
        if self._audit is not None:
            ts = time.time()
            lines = []
            for i, name in enumerate(meta.pod_names):
                n = int(ni[i])
                lines.append(json.dumps(dict(
                    ts=ts, kind="placement", pod=name,
                    node=meta.node_names[n] if n >= 0 else None,
                    score=round(float(sc[i]), 4),
                    commit_key=int(ck[i]), snapshot_id=sid,
                )))
            for name in resp.evicted:
                lines.append(json.dumps(dict(
                    ts=ts, kind="eviction", pod=name, snapshot_id=sid,
                )))
            # One write per batch under a lock: concurrent handlers must
            # not interleave partial lines into the audit log.
            if lines:
                with self._audit_lock:
                    self._audit.write("\n".join(lines) + "\n")
                    self._audit.flush()
        resp.rounds = res.rounds
        resp.solve_seconds = res.solve_seconds
        self._log_batch("Assign", meta, decode_s, res.solve_seconds,
                        placed, n_evicted, res.rounds)
        self.metrics.observe(meta.n_pods, placed, n_evicted,
                             decode_s + res.solve_seconds)
        return resp

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        return pb.HealthResponse(
            ok=True, backend=jax.default_backend(), devices=len(jax.devices())
        )

    def Metrics(self, request: pb.MetricsRequest, context) -> pb.MetricsResponse:
        return pb.MetricsResponse(prometheus_text=self.metrics.render())


def make_server(
    address: str = "127.0.0.1:0",
    config: EngineConfig | None = None,
    buckets: Buckets | None = None,
    max_workers: int = 4,
    log_stream=None,
    audit_stream=None,
):
    """Build (grpc.Server, bound_port, service). Unlimited message size:
    a 10k-pod snapshot exceeds the 4 MB default."""
    svc = SchedulerService(config, buckets, log_stream=log_stream,
                           audit_stream=audit_stream)

    def handler(fn, req_cls):
        return grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )

    table = {
        "ScoreBatch": handler(svc.ScoreBatch, pb.ScoreRequest),
        "Assign": handler(svc.Assign, pb.AssignRequest),
        "Health": handler(svc.Health, pb.HealthRequest),
        "Metrics": handler(svc.Metrics, pb.MetricsRequest),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", -1),
            ("grpc.max_send_message_length", -1),
        ],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, table),)
    )
    port = server.add_insecure_port(address)
    return server, port, svc


def serve(address: str = "127.0.0.1:50051", config: EngineConfig | None = None,
          audit_path: str | None = None):
    """Blocking entry point: python -m tpusched.rpc.server"""
    audit = open(audit_path, "a") if audit_path else None
    server, port, _ = make_server(address, config, audit_stream=audit)
    server.start()
    print(f"tpusched sidecar listening on port {port}", file=sys.stderr)
    server.wait_for_termination()


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--address", default="127.0.0.1:50051")
    ap.add_argument("--config", default=None, help="EngineConfig YAML path")
    ap.add_argument("--audit", default=None,
                    help="append per-pod placement audit JSONL to this file")
    args = ap.parse_args()
    cfg = None
    if args.config:
        from tpusched.config import load_config

        cfg = load_config(args.config)
    serve(args.address, cfg, audit_path=args.audit)
