"""The TPU scheduling sidecar (SURVEY.md C12): a gRPC server wrapping
Engine. This is the process a `--score-backend=tpu` scheduler talks to
(BASELINE.json:"north_star").

Service stubs are hand-wired with grpc generic handlers (the image has
protoc + grpcio but no grpc_tools codegen); the method table mirrors
protos/tpusched.proto's service block.

Request handling is STAGED (round 6, SURVEY.md §2.3 PP in-request):
decode runs outside the serialized dispatch section (concurrent across
handler threads), the dispatch slot is held just long enough to
enqueue the program (Engine.solve_async / score_topk_async — one
ordered background fetch worker), and the response's name tables build
while the device runs. A single pipelined connection (client
AssignPipeline / ScorePipeline, depth 2) therefore overlaps request
k+1's decode with request k's solve, and even a strictly sequential
client gets its response scaffolding for free inside the device window.

Round 7 makes the sidecar MULTI-CLIENT (ISSUE 2 tentpole):

  * DeviceSession keeps each delta lineage's cluster state RESIDENT on
    the device — deltas apply as O(churn) scatter updates
    (tpusched/device_state.py) instead of recompose + full decode +
    full H2D;
  * the dispatch mutex became _DispatchGate, a bounded FAIR queue
    (round-robin across client peers, FIFO within one, admission caps
    -> RESOURCE_EXHAUSTED);
  * _ScoreCoalescer fuses concurrent identical ScoreBatch deltas into
    one padded top-k dispatch, sliced per caller.

Observability (SURVEY.md §5): every batch emits one structured JSON log
line (sizes, rounds, per-phase seconds, placements/sec) on stderr, and
the Metrics rpc serves Prometheus text with upstream-compatible metric
names (scheduler_e2e_scheduling_duration_seconds etc.).

Round 8 (ISSUE 3) gives the sidecar a FAILURE-DOMAIN CONTRACT.

Error taxonomy — every status the sidecar returns falls in one of
three classes, and the client (rpc/client.py RetryPolicy) keys its
behavior off the class, never the message text:

  RETRYABLE (same request may succeed soon; capped backoff + retry)
    UNAVAILABLE          channel down / sidecar restarting
    RESOURCE_EXHAUSTED   dispatch-gate admission refused (queue full)
  RESYNC-REQUIRED (retrying the same delta can NEVER succeed; the
  client must fall back to a full snapshot and re-pin)
    FAILED_PRECONDITION  unknown/expired base_id, seq replayed past the
                         dedupe cache, or stateless degraded mode
  FATAL (a bug in the request or the server; retrying is wrong)
    INVALID_ARGUMENT     malformed delta (no base_id, duplicate names)
    DEADLINE_EXCEEDED    per-dispatch watchdog fired (the REQUEST is
                         dead; the server stays healthy — callers may
                         re-submit as a NEW cycle, not a blind retry)
    INTERNAL             unexpected server exception

Retry-safety: deltas carry (lineage_id, seq); a retried delta whose
first attempt was applied-but-unacked replays the cached response
instead of re-applying (SnapshotDelta proto comment).

Watchdog: every device-result join runs under `watchdog_s`; a hung
solve becomes DEADLINE_EXCEEDED for ITS caller, the wedged fetch
worker is abandoned (Engine.restart_fetch_worker), and the server
keeps serving other clients — a stuck dispatch can no longer wedge
the gate.

Degradation ladder (DegradationLadder): repeated device-path failures
quarantine the fast path one rung at a time —

    delta      device-resident DeviceSessions, O(churn) serving
    rebuild    sessions quarantined: every delta recomposes bytes and
               fully re-decodes (correct, slower)
    stateless  deltas refused (FAILED_PRECONDITION) and snapshot_ids
               withheld: clients full-send every cycle; the sidecar
               holds NO cross-request state a fault could corrupt

with automatic probe-based recovery: after a cooldown with successes,
the ladder promotes one rung on probation — one failure at the
restored rung demotes immediately, a success keeps it. Health reports
the rung and counters; Metrics exports them.

Round 11 (ISSUE 6) makes the sidecar a FLEET MEMBER instead of a
single point of failure. Every store registration (full send or delta)
is appended to a ReplicationLog (tpusched/replicate.py) as an op that
carries the SAME snapshot_id handed to the client; the Replicate rpc
serves that log to standby replicas, whose StandbyFollower applies the
ops into their own byte stores (and warms DeviceSessions), so a
failed-over client's delta against a leader-era base_id resolves on
the standby without a resync storm. Roles: a "leader" serves and
appends; a "standby" follows and serves only Health/Metrics/Debugz/
Replicate until its first Assign/ScoreBatch arrives — which PROMOTES
it (takeover: one trace event + flight dump carrying the hand-off
causal chain; the "replica.takeover" fault site can refuse it with
UNAVAILABLE — the split-brain-attempt guard scenario). Replication is
async: an op in flight when the leader dies is lost SAFELY — the
failed-over client gets FAILED_PRECONDITION and the ISSUE 3 resync
machinery re-sends the full snapshot.

Round 9 (ISSUE 4) makes the whole pipeline OBSERVABLE:

  * every handler roots a trace (tpusched.trace) at the request's
    wire request_id/parent_span (client-minted; absent => server-
    minted) and emits one span per stage — gate.wait, decode,
    delta.apply (+H2D bytes), dispatch, coalesce.lead/coalesce.wait,
    fetch.join, reply.pack — ring-buffered, exported by the Debugz
    rpc and tools/tracez.py as Chrome/Perfetto trace-event JSON;
  * a FlightRecorder snapshots the ring on watchdog trips, ladder
    demotions, and resync storms (>= 4 FAILED_PRECONDITION answers in
    5 s), so every PR-3 degradation event carries its causal trace;
  * _Metrics is a labeled registry (tpusched.metrics): per-rpc
    counters, per-stage log-scale histograms (the old 5s-capped
    buckets parked every real 10k x 5k solve in +Inf), H2D byte and
    fuse-size histograms, and request outcome counts by status code.
"""

from __future__ import annotations

import hashlib
import json
import logging
import sys
import threading
import time
import traceback
import uuid
from collections import deque
from concurrent import futures
from concurrent.futures import TimeoutError as _FutTimeout
from contextlib import contextmanager

import jax
import numpy as np

import grpc

from tpusched import explain as explaining
from tpusched import ledger as ledgering
from tpusched import metrics as pm
from tpusched import shapeclass
from tpusched import trace as tracing
from tpusched import wire as wiring
from tpusched.faults import NO_FAULTS
from tpusched.mesh import make_mesh
from tpusched.config import Buckets, EngineConfig
from tpusched.device_state import DeviceQueue, DeviceSnapshot
from tpusched.ingest import IngestGate
from tpusched.replicate import ReplicationLog
from tpusched.engine import Engine
from tpusched.faults import FaultError
from tpusched.rpc import tpusched_pb2 as pb
from tpusched.rpc import codec
from tpusched.rpc.codec import SnapshotStore, decode_snapshot, delta_safe
from tpusched.trace import FlightRecorder, StormDetector

SERVICE = "tpusched.TpuScheduler"

# Recent snapshot stores kept for delta resolution. Each store holds
# references into decoded request protos (cheap); the cap bounds memory
# and defines how stale a client's base_id may be before it must resend
# a full snapshot. Sized for MULTI-CLIENT fan-in (round 7): K chained
# lineages each need their latest base plus one in flight to survive
# the LRU while the other K-1 register new stores every cycle — 8 was
# borderline at K=4 and forced periodic full resends + device-session
# re-seeds.
STORE_CAP = 32

# Device-resident lineages kept alive concurrently (each holds a full
# cluster's arrays on the accelerator, so the cap is memory, not CPU).
DEVICE_SESSION_CAP = 8

# Per-dispatch watchdog default: how long a handler waits on a device
# result before declaring the solve hung (DEADLINE_EXCEEDED + fetch
# worker abandoned). Generous — a 10k x 5k parity solve on a loaded
# CPU host takes tens of seconds; the watchdog exists for WEDGED
# dispatches (a transport hang, a stuck D2H), not slow ones.
WATCHDOG_S = 120.0

# Replayable responses kept per delta lineage for seq dedupe. A depth-2
# pipeline has at most 2 unacked requests in flight; 4 leaves margin
# for a retry racing a new submit. Responses above REPLAY_MAX_BYTES are
# NOT cached (a full-matrix ScoreBatch at 10k x 5k is ~250 MB; 4 per
# lineage x 32 lineages would be multi-GB): deterministic solves make
# re-processing an uncached retry safe — it re-applies against the
# still-stored base and rebuilds the identical response.
REPLAY_PER_LINEAGE = 4
REPLAY_MAX_BYTES = 8 << 20

# Above this many matrix cells a packed_ok ScoreBatch response switches
# from repeated ScoreRow to the packed-bytes form: the row form costs
# one pure-Python proto setter per cell (5*10^7 floats + bools at
# 10k x 5k — minutes, round-3 verdict missing #2), the packed form two
# ndarray.tobytes() calls.
PACK_CELLS = 1 << 15


class _Metrics:
    """Labeled Prometheus registry for the serving path (round 9,
    ISSUE 4 — replaces four unlabeled counters + one 5s-capped
    histogram). Built on tpusched.metrics: every family gets a `# TYPE`
    line, label values are escaped, histograms emit `_sum`/`_count`,
    and bucket ranges are shape-aware — durations log-scale out past
    the watchdog (a 10k x 5k CPU solve runs far beyond the old 5.0s
    top bucket, which parked every real solve in +Inf), H2D bytes in
    power-of-4 byte buckets, fuse sizes in small linear buckets.

    Upstream-compatible names are kept (scheduler_schedule_attempts_
    total etc.), now labeled by rpc; per-stage serving telemetry lands
    in scheduler_stage_duration_seconds{stage=...} where stage follows
    the trace span names (gate.wait, decode, delta.apply, dispatch,
    fetch.join, reply.pack) so a histogram anomaly points at the same
    name a trace shows."""

    def __init__(self):
        r = self.registry = pm.Registry()
        self.attempts = pm.Counter(
            "scheduler_schedule_attempts_total",
            "pods offered to the solver", ("rpc",), registry=r)
        self.placements = pm.Counter(
            "scheduler_pod_placements_total",
            "pods placed", ("rpc",), registry=r)
        self.evictions = pm.Counter(
            "scheduler_preemption_victims_total",
            "running pods evicted by preemption", ("rpc",), registry=r)
        self.batches = pm.Counter(
            "scheduler_batches_total",
            "request batches served", ("rpc",), registry=r)
        self.requests = pm.Counter(
            "scheduler_requests_total",
            "requests by final grpc status", ("rpc", "code"), registry=r)
        self.resyncs = pm.Counter(
            "scheduler_resync_required_total",
            "FAILED_PRECONDITION answers (client must full-resync)",
            ("rpc",), registry=r)
        self.overloaded = pm.Counter(
            "scheduler_overloaded_total",
            "dispatch-gate admission refusals", ("rpc",), registry=r)
        self.e2e = pm.Histogram(
            "scheduler_e2e_scheduling_duration_seconds",
            "decode + solve wall per batch",
            buckets=pm.DURATION_BUCKETS, labelnames=("rpc",), registry=r)
        self.stage = pm.Histogram(
            "scheduler_stage_duration_seconds",
            "per-stage serving latency (stage == trace span name)",
            buckets=pm.DURATION_BUCKETS, labelnames=("stage",), registry=r)
        self.h2d = pm.Histogram(
            "scheduler_h2d_bytes",
            "host->device bytes shipped per delta cycle",
            buckets=pm.BYTE_BUCKETS, labelnames=("path",), registry=r)
        # Wire ledger (round 19, ISSUE 19): per-direction bytes at the
        # serving boundary plus a reply-size histogram — before this,
        # only H2D bytes had a family and the reply/D2H direction was
        # entirely unaccounted.
        self.wire_bytes = pm.Counter(
            "scheduler_wire_bytes",
            "serialized request/reply bytes at the serving boundary",
            ("direction", "rpc"), registry=r)
        self.reply_bytes = pm.Histogram(
            "scheduler_reply_bytes",
            "serialized reply payload per served request",
            buckets=pm.BYTE_BUCKETS, labelnames=("rpc",), registry=r)
        self.fuse = pm.Histogram(
            "scheduler_coalesced_fuse_size",
            "callers sharing one coalesced ScoreBatch dispatch",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16), registry=r)
        # Decision provenance (round 12): outcome counts and pending
        # causes, incremented per EXPLAINED cycle only (explain=off
        # cycles don't classify — the counters say so in the help).
        self.decisions = pm.Counter(
            "scheduler_decisions_total",
            "pod decision outcomes on explained cycles", ("outcome",),
            registry=r)
        self.pending_reasons = pm.Counter(
            "scheduler_pending_pods_total",
            "pending-pod causes on explained cycles (dominant filter "
            "reason, or outranked when feasible nodes existed)",
            ("reason",), registry=r)
        # Commit-round + warm-path observability (round 17, ISSUE 12):
        # the frontier-compaction win is a ROUND-COUNT story, so rounds
        # get a first-class histogram instead of living only in the
        # per-batch JSON log lines, and every Assign solve is labeled
        # by the path that produced it — cold (the plain packed solve),
        # bitwise (warm tableau, placements == cold), or incremental
        # (bounded-divergence frontier rounds).
        self.solve_rounds = pm.Histogram(
            "scheduler_solve_rounds",
            "commit rounds per solved Assign batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256), registry=r)
        self.warm_solves = pm.Counter(
            "scheduler_warm_solves_total",
            "Assign solves by warm path (bitwise|incremental|cold)",
            ("path",), registry=r)

    def observe(self, n_pods: int, n_placed: int, n_evicted: int,
                dur: float, rpc: str = "Assign"):
        self.attempts.labels(rpc).inc(n_pods)
        self.placements.labels(rpc).inc(n_placed)
        self.evictions.labels(rpc).inc(n_evicted)
        self.batches.labels(rpc).inc()
        self.e2e.labels(rpc).observe(dur)

    def observe_stage(self, stage: str, dur_s: float) -> None:
        self.stage.labels(stage).observe(dur_s)

    def count_request(self, rpc: str, code: str) -> None:
        self.requests.labels(rpc, code).inc()

    def render(self) -> str:
        return self.registry.render()


class DegradationLadder:
    """Quarantine state machine for the device fast path (module
    docstring, "Degradation ladder").

    Demotion: `demote_after` CONSECUTIVE failures at the current rung
    (or a single failure while on probation) drop one rung. Recovery:
    once `recover_after_s` has passed since the demotion AND at least
    one success has landed at the degraded rung, the next level() read
    promotes one rung ON PROBATION — the probe. All transitions are
    clock-injectable and deterministic for tests."""

    LEVELS = ("delta", "rebuild", "stateless")

    def __init__(self, demote_after: int = 2, recover_after_s: float = 30.0,
                 clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.demote_after = int(demote_after)
        self.recover_after_s = float(recover_after_s)
        self._idx = 0
        self._consec_failures = 0
        self._demoted_at: float | None = None
        self._successes_since_demote = 0
        self._probation = False
        self.demotions = 0
        self.recoveries = 0

    def level(self) -> str:
        """Current rung; performs the probe-promotion check."""
        with self._lock:
            self._maybe_promote_locked()
            return self.LEVELS[self._idx]

    def record_success(self) -> None:
        with self._lock:
            self._consec_failures = 0
            self._probation = False  # the probe survived: rung is kept
            self._successes_since_demote += 1

    def record_failure(self) -> bool:
        """One device-path failure; returns True when it demoted."""
        with self._lock:
            self._consec_failures += 1
            trip = (self._probation
                    or self._consec_failures >= self.demote_after)
            if trip and self._idx < len(self.LEVELS) - 1:
                self._idx += 1
                self.demotions += 1
                self._consec_failures = 0
                self._probation = False
                self._demoted_at = self._clock()
                self._successes_since_demote = 0
                return True
            return False

    def _maybe_promote_locked(self) -> None:
        if (
            self._idx > 0
            and self._demoted_at is not None
            and self._successes_since_demote > 0
            and self._clock() - self._demoted_at >= self.recover_after_s
        ):
            self._idx -= 1
            self.recoveries += 1
            self._probation = True
            self._successes_since_demote = 0
            # Still degraded after the promotion: arm the next probe.
            self._demoted_at = self._clock() if self._idx else None

    def snapshot(self) -> dict:
        with self._lock:
            return dict(
                level=self.LEVELS[self._idx],
                demotions=self.demotions,
                recoveries=self.recoveries,
                probation=self._probation,
            )


class _Abort(Exception):
    """Internal abort carrier: raised where the old code called
    context.abort directly, so COALESCED requests can relay the same
    status to every fused caller (each grpc context must abort itself)."""

    def __init__(self, code, details: str):
        super().__init__(details)
        self.code = code
        self.details = details


class _Overloaded(Exception):
    """Dispatch gate admission refused (queue caps hit)."""


class _DispatchGate:
    """Bounded FAIR admission to the device dispatch slot — the
    replacement for the old `_dispatch_lane` mutex.

    A plain lock serializes dispatches but hands the slot to whichever
    gRPC thread the OS wakes first: one chatty client can starve the
    rest, and tail latency under fan-in is whoever loses the race
    longest. The gate keeps one FIFO queue per client (peer string) and
    serves queue HEADS round-robin, so K clients each see every K'th
    slot — Assign streams from distinct clients interleave at round
    granularity — while one client's own requests stay ordered.

    Admission is BOUNDED: beyond `max_waiting_per_client` queued
    entries for one client (a runaway pipeline) or `max_waiting` total,
    acquire raises _Overloaded and the handler answers
    RESOURCE_EXHAUSTED instead of building an unbounded queue.
    """

    def __init__(self, max_waiting_per_client: int = 16,
                 max_waiting: int = 128):
        self._cv = threading.Condition()
        self._queues: dict[str, deque] = {}
        self._order: list[str] = []     # clients with waiters, stable order
        self._last: str | None = None   # round-robin pointer
        self._busy = False
        self._waiting = 0
        self._closed = False
        self.max_waiting_per_client = max_waiting_per_client
        self.max_waiting = max_waiting
        # Observability: served slots + peak depth.
        self.served = 0
        self.peak_waiting = 0

    def _choose(self):
        """(client, head ticket) the slot goes to next, by round-robin
        from the client AFTER the last served one."""
        order = self._order
        if not order:
            return None, None
        start = 0
        if self._last in order:
            start = order.index(self._last) + 1
        for i in range(len(order)):
            c = order[(start + i) % len(order)]
            q = self._queues.get(c)
            if q:
                return c, q[0]
        return None, None

    @contextmanager
    def slot(self, client: str):
        self._acquire(client)
        try:
            yield
        finally:
            self._release()

    def _acquire(self, client: str) -> None:
        me = object()
        with self._cv:
            if self._closed:
                raise _Overloaded("server shutting down")
            q = self._queues.get(client)
            if self._waiting >= self.max_waiting:
                raise _Overloaded(
                    f"dispatch queue full ({self.max_waiting} waiting)"
                )
            if q is not None and len(q) >= self.max_waiting_per_client:
                raise _Overloaded(
                    f"client {client!r} has {len(q)} dispatches queued "
                    f"(cap {self.max_waiting_per_client})"
                )
            if q is None:
                q = self._queues[client] = deque()
                self._order.append(client)
            q.append(me)
            self._waiting += 1
            self.peak_waiting = max(self.peak_waiting, self._waiting)
            while True:
                if self._closed:
                    self._evict(client, me)
                    raise _Overloaded("server shutting down")
                if not self._busy:
                    c, head = self._choose()
                    if head is me:
                        break
                self._cv.wait()
            # Our turn: take the slot and advance the round-robin.
            self._busy = True
            self._last = client
            self._evict(client, me)
            self.served += 1

    def _evict(self, client: str, me) -> None:
        q = self._queues.get(client)
        if q is not None and me in q:
            q.remove(me)
            self._waiting -= 1
            if not q:
                del self._queues[client]
                self._order.remove(client)

    def _release(self) -> None:
        with self._cv:
            self._busy = False
            self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class _Fusion:
    """One coalesced ScoreBatch dispatch: the LEADER resolves, decodes,
    dispatches once with k = max over joined callers, and publishes;
    followers wait and slice their own k columns from the shared
    result. Joining closes when the leader reaches the dispatch slot."""

    def __init__(self, key):
        self.key = key
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._ks: list[int] = []
        self._sealed = False
        self._payload = None
        self._error: tuple | None = None

    def try_join(self, k: int) -> bool:
        with self._lock:
            if self._sealed:
                return False
            self._ks.append(int(k))
            return True

    def seal(self) -> int:
        """Stop admitting joiners; returns the fused k (max)."""
        with self._lock:
            self._sealed = True
            return max(self._ks) if self._ks else 0

    def publish(self, payload) -> None:
        self._payload = payload
        self._event.set()

    def fail(self, code, details: str) -> None:
        self._error = (code, details)
        self._event.set()

    def wait(self, timeout: float):
        if not self._event.wait(timeout):
            raise _Abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                         "coalesced dispatch leader timed out")
        if self._error is not None:
            raise _Abort(self._error[0],
                         f"coalesced leader failed: {self._error[1]}")
        return self._payload


class _ScoreCoalescer:
    """Request-level fusion of concurrent ScoreBatch DELTAS against the
    same store: identical (base_id, delta bytes) means identical
    post-delta cluster state, so N callers' matrices are one padded
    device dispatch — resolve, decode/apply, and rank run ONCE, and
    per-caller top_k differences collapse to a column slice (lax.top_k
    is prefix-stable: the first k_i of top k_max IS top k_i)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: dict = {}
        self.fused_requests = 0   # followers served without a dispatch
        self.lead_requests = 0

    def join(self, key, k: int):
        """(fusion, is_leader)."""
        with self._lock:
            f = self._pending.get(key)
            if f is not None and f.try_join(k):
                self.fused_requests += 1
                return f, False
            f = _Fusion(key)
            f.try_join(k)
            self._pending[key] = f
            self.lead_requests += 1
            return f, True

    def finish(self, fusion) -> None:
        with self._lock:
            if self._pending.get(fusion.key) is fusion:
                del self._pending[fusion.key]


class DeviceSession:
    """One delta lineage's device-resident cluster state (SURVEY.md §7
    hard part 6 + the tentpole of this round): wire deltas apply as
    on-device scatter updates through DeviceSnapshot instead of
    recompose-bytes -> full decode -> full H2D.

    A session answers deltas against TWO base ids:

      * its PIN — the base it was seeded from. Pipelined clients
        (AssignPipeline / ScorePipeline) send CUMULATIVE deltas that
        all name the pin; the session applies cumulative delta k+1 on
        top of cumulative delta k by also RESTORING pin records that
        delta k touched but k+1 no longer mentions (a record mutated
        back to its pin content drops out of the diff).
      * its CURRENT snapshot_id — chain clients (DeltaSession) target
        the previous response's sid; serving that id re-pins the
        session there (shallow record-dict copies, O(records) pointer
        work).

    A fork (a second delta against a base the session has moved past)
    simply misses and takes the decode path."""

    def __init__(self, device: DeviceSnapshot, pin_sid: str):
        self.device = device
        self.lock = threading.Lock()
        self.last_stats = None   # ApplyStats of the latest load/apply
        self._pin_sid = pin_sid
        self._cur_sid = pin_sid
        self._pin = (dict(device._nodes), dict(device._pods),
                     dict(device._running))
        # Names churned since the pin, per collection.
        self._touched: tuple[set, set, set] = (set(), set(), set())

    def keys(self) -> set[str]:
        return {self._pin_sid, self._cur_sid}

    @classmethod
    def from_base_store(cls, store: SnapshotStore, base_id: str,
                        config: EngineConfig,
                        buckets: Buckets | None,
                        mesh=None) -> "DeviceSession":
        """Seed from the BASE (pre-delta) byte store so the pin matches
        what pipelined clients keep diffing against (the one-time
        O(cluster) conversion; every later delta is O(churn)). mesh:
        shard the lineage arrays in the canonical layout so warm
        dispatches on a mesh-backed engine read them in place."""
        def parse(cls_pb, raw):
            return cls_pb.FromString(raw) if isinstance(raw, bytes) else raw

        nodes = [codec.node_kwargs(parse(pb.Node, v))
                 for v in store.nodes.values()]
        pods = [codec.pod_kwargs(parse(pb.PendingPod, v))
                for v in store.pods.values()]
        running = [codec.running_kwargs(parse(pb.RunningPod, v))
                   for v in store.running.values()]
        device = DeviceSnapshot(config, buckets, mesh=mesh)
        stats = device.full_load(nodes, pods, running)
        session = cls(device, pin_sid=base_id)
        session.last_stats = stats
        return session

    def apply_delta(self, base_id: str, delta: "pb.SnapshotDelta",
                    new_sid: str):
        """Advance to base_id + delta. base_id must be one of keys()."""
        if base_id == self._cur_sid and base_id != self._pin_sid:
            # Chain step: the client committed to the current state —
            # re-pin here (shallow copies; record dicts are replaced,
            # never mutated, so sharing them is safe).
            dev = self.device
            self._pin = (dict(dev._nodes), dict(dev._pods),
                         dict(dev._running))
            self._pin_sid = base_id
            self._touched = (set(), set(), set())
        elif base_id != self._pin_sid:
            raise KeyError(f"session cannot serve base {base_id!r}")
        up_n = [codec.node_kwargs(n) for n in delta.upsert_nodes]
        up_p = [codec.pod_kwargs(p) for p in delta.upsert_pods]
        up_r = [codec.running_kwargs(r) for r in delta.upsert_running]
        rm_n = list(delta.remove_nodes)
        rm_p = list(delta.remove_pods)
        rm_r = list(delta.remove_running)
        new_touched = (
            {r["name"] for r in up_n} | set(rm_n),
            {r["name"] for r in up_p} | set(rm_p),
            {r["name"] for r in up_r} | set(rm_r),
        )
        # Restore pin records the previous cumulative delta touched but
        # this one no longer mentions (mutated back to pin content).
        for prev, new, pin, ups, rms in (
            (self._touched[0], new_touched[0], self._pin[0], up_n, rm_n),
            (self._touched[1], new_touched[1], self._pin[1], up_p, rm_p),
            (self._touched[2], new_touched[2], self._pin[2], up_r, rm_r),
        ):
            for name in prev - new:
                if name in pin:
                    ups.append(pin[name])
                else:
                    rms.append(name)
        self.last_stats = self.device.apply(
            upsert_nodes=up_n, remove_nodes=rm_n,
            upsert_pods=up_p, remove_pods=rm_p,
            upsert_running=up_r, remove_running=rm_r,
        )
        self._touched = new_touched
        self._cur_sid = new_sid
        return self.last_stats


class SchedulerService:
    def __init__(
        self,
        config: EngineConfig | None = None,
        buckets: Buckets | None = None,
        log_stream=None,
        audit_stream=None,
        device_sessions: int = DEVICE_SESSION_CAP,
        faults=None,
        watchdog_s: float = WATCHDOG_S,
        ladder: DegradationLadder | None = None,
        tracer: "tracing.TraceCollector | None" = None,
        flight: FlightRecorder | None = None,
        role: str = "leader",
        replication_log: "ReplicationLog | None" = None,
        explain=False,
        explain_k: int = 3,
        warm: "str | None" = None,
        ledger: "ledgering.CycleLedger | None" = None,
        ledger_jsonl: "str | None" = None,
        prewarm: bool = False,
        wire: "wiring.WireLedger | None" = None,
        wire_profile_dir: "str | None" = None,
        ingest=None,
    ):
        """audit_stream: optional file-like; when set, every Assign
        emits one JSON record PER POD (pod, node, score, commit_key —
        the upstream per-pod placement-decision audit, SURVEY.md §5
        'Metrics/observability') plus one per eviction. Off by default:
        at 10k pods a full audit is ~1 MB per batch.

        device_sessions: how many delta lineages keep their cluster
        state RESIDENT on the device (0 disables; every delta then
        recomposes + fully re-decodes as before).

        faults: optional tpusched.faults.FaultPlan, shared with the
        engine — sites "server.decode" and "server.session" here,
        "engine.fetch" inside the fetch worker (chaos harness).

        watchdog_s: per-dispatch result-join budget; a solve that has
        not landed in time becomes DEADLINE_EXCEEDED for its caller and
        the wedged fetch worker is abandoned (module docstring).

        ladder: injectable DegradationLadder (tests pin the clock).

        tracer: span collector (default: the process-wide
        tpusched.trace.DEFAULT, so in-process clients and the sidecar
        share one stitched ring). flight: injectable FlightRecorder.

        role: "leader" (serves + appends every store registration to
        its replication log) or "standby" (follows a leader's log via
        StandbyFollower; the first Assign/ScoreBatch promotes it —
        module docstring, round 11). replication_log: injectable
        ReplicationLog (tests pin capacity to force the rebase path).

        explain (round 12, ISSUE 8): decision provenance. True (or an
        injected tpusched.explain.ExplainCollector) makes every Assign
        an EXPLAINED cycle — the engine additionally runs the lazily-
        compiled provenance programs (per-pod outcome + top-k score
        decomposition + filter tallies + victim chains) and one
        DecisionRecord lands in the collector, served by the Explainz
        rpc and carried in flight-recorder dumps. Off (default) the
        serving path is byte-identical to round 11: one enabled-check
        per Assign. explain_k: candidate depth per pod.

        warm (round 17, ISSUE 12): None (default) keeps every Assign on
        the plain packed solve; "bitwise" routes delta Assigns whose
        lineage has a live DeviceSession through the warm-tableau path
        (placements bitwise == cold); "incremental" through the
        bounded-divergence frontier path (solve time scales with the
        delta's churn — the in-kernel validity audit rides
        SolveResult.inc_info). Either way full-send Assigns, explained
        cycles, forks, and degraded rungs fall back to the plain solve,
        and scheduler_warm_solves_total{path} counts what actually
        served.

        ledger (round 18, ISSUE 13): injectable
        tpusched.ledger.CycleLedger; by default the service builds its
        own, registered in ITS metrics registry (so
        scheduler_cycle_anomalies_total and friends render in this
        server's Metrics rpc) and wired to its flight recorder and
        span ring (an anomaly's flight dump carries the causal trace).
        Every served Assign appends one CycleRecord — stage walls
        joined from the request's spans, delta churn, warm path,
        commit rounds, and the XLA cache misses the request paid —
        served by the Statusz rpc / tools/statusz.py. ledger_jsonl:
        optional path for the JSONL black box (every record appended;
        ignored when `ledger` is injected).

        prewarm (PR 18, ROADMAP item 3): True traces EVERY shape class
        in the registry derived from (config, buckets, explain, warm) on
        a background boot thread — requires explicit `buckets` (no
        finite shape set exists otherwise). `prewarm_complete` flips
        when done (Health field 12; ReplicaSet.wait_caught_up blocks on
        it for standbys, so a promotion serves its first Assign with
        zero new compiles). Compiles traced during boot land in
        ledger.COMPILES with cause="prewarm".

        wire (round 19, ISSUE 19): injectable tpusched.wire.WireLedger;
        by default the service builds its own, registered in ITS
        metrics registry and wired to its flight recorder / span ring
        — the server HOLDS the ledger (Statusz `wire` panel, anomaly
        counters) while clients FEED it: an in-process or loopback
        client constructed with wire=svc.wire assembles each cycle's
        WireRecord from the shared span ring. wire_profile_dir: when
        set, a wire anomaly arms a one-shot jax.profiler device-trace
        capture of the next serving cycle (WireLedger.maybe_profile),
        written under this directory.

        ingest (PR 20, ISSUE 20): the admission-controlled front door
        served by the Enqueue rpc. None (default) leaves Enqueue
        UNIMPLEMENTED. An IngestGate instance is used as-is; any other
        truthy value builds a gate over a fresh DeviceQueue — pass a
        dict of knobs (capacity/bound for the queue; rate/burst/
        tenants/skew for tpusched.ingest.IngestGate) or True for the
        defaults. A built gate registers its families in THIS server's
        metrics registry, ledgers its drain records into THIS server's
        cycle ledger (source="ingest"), shares the fault plan (the
        ``ingest.enqueue`` site), and dedups admitted names so a
        shed-then-retried batch converges exactly-once."""
        self.config = config or EngineConfig()
        # Floor buckets pin compile shapes across requests (a feature
        # first appearing mid-serving would otherwise trigger a full
        # recompile stall; SnapshotBuilder docstring caveat).
        self.buckets = buckets
        self.metrics = _Metrics()
        # A configured mesh shape (or the ring path, which needs a
        # mesh) puts the sidecar's engine on a device mesh — the YAML
        # route to the sharded/ring paths (EngineConfig.mesh_shape).
        mesh = None
        if self.config.ring_counts or tuple(self.config.mesh_shape) != (1, 1):
            shape = tuple(self.config.mesh_shape)
            mesh = make_mesh(None if shape == (1, 1) else shape)
        self._faults = faults if faults is not None else NO_FAULTS
        # Device sessions shard their lineage arrays over the same mesh
        # the engine solves on (ROADMAP item 1: the snapshot a solve
        # reads and the lineage the deltas scatter into share one
        # canonical layout — no per-dispatch reshard).
        self._mesh = mesh
        self._engine = Engine(self.config, mesh=mesh, faults=self._faults)
        self._log = log_stream if log_stream is not None else sys.stderr
        self._audit = audit_stream
        self._audit_lock = threading.Lock()  # handlers run on a pool
        self._store_lock = threading.Lock()
        self._stores: dict[str, SnapshotStore] = {}  # LRU by insertion
        self._next_store = 0
        # Mint EPOCH (round 11): sids carry a per-instance nonce so a
        # promoted standby's own mints can NEVER alias a sid the dead
        # leader handed a client inside the async-replication loss
        # window — an aliased base would silently resolve a failed-over
        # delta against the wrong bytes instead of triggering the
        # FAILED_PRECONDITION -> resync heal path.
        self._mint_nonce = uuid.uuid4().hex[:8]
        self._last_minted: str | None = None  # newest REGISTERED sid
        # Dispatch admission (round 7, replaces the `_dispatch_lane`
        # mutex): handlers still decode OUTSIDE the serialized section
        # and build responses while the engine's ordered fetch worker
        # drives the device — but the slot itself is now a bounded FAIR
        # queue (round-robin across clients, FIFO within one), and
        # concurrent ScoreBatch deltas against the same store fuse into
        # one dispatch (_ScoreCoalescer). Dispatch order == fetch order
        # still holds: only the slot holder dispatches.
        self._gate = _DispatchGate()
        self._coalescer = _ScoreCoalescer()
        # Device-resident lineages: current snapshot_id -> DeviceSession
        # (LRU by insertion, capped — each holds a cluster on device).
        self._session_cap = device_sessions
        if warm not in (None, "bitwise", "incremental"):
            raise ValueError(
                f"warm={warm!r}: want None, 'bitwise', or 'incremental'"
            )
        self._warm = warm
        self._sessions: dict[str, DeviceSession] = {}
        self._seeding: set[str] = set()   # base_ids mid-seed (dedupe)
        self.session_seeds = 0
        self.session_hits = 0
        self.session_misses = 0
        # Failure-domain state (round 8, ISSUE 3): watchdog budget,
        # degradation ladder, and the per-lineage seq replay cache.
        self.watchdog_s = watchdog_s
        self.watchdog_trips = 0
        self._ladder = ladder if ladder is not None else DegradationLadder()
        self._watchdog_lock = threading.Lock()
        self._last_worker_restart = 0.0
        # lineage_id -> {(seq, rpc): response message}; LRU at both
        # levels. Deterministic solves make an evicted entry SAFE to
        # re-process — the replay is an optimization plus the dedupe
        # guarantee for the applied-but-unacked retry window.
        self._replay_lock = threading.Lock()
        self._replay: dict[str, dict] = {}
        self.replayed_requests = 0
        # Observability (round 9, ISSUE 4): span collector, flight
        # recorder (ring snapshots on failure events), and the resync-
        # storm detector feeding it.
        self._trace = tracer if tracer is not None else tracing.DEFAULT
        if tracer is not None:
            # Non-default collector: point the emitters this service
            # owns (engine.fetch, fault.* shots, device.rebuild via
            # DeviceSession seeding below) at the same ring, so Debugz
            # and flight dumps still carry the full causal chain.
            self._engine.tracer = tracer
            self._faults.tracer = tracer
        self.flight = flight if flight is not None else FlightRecorder()
        # Decision provenance (round 12, ISSUE 8): collector + the
        # flight-recorder attachment (dumps carry last-N decisions).
        # ONE source for the candidate depth: an injected collector's
        # topk wins (host-side wiring honors the same field); explain_k
        # only applies when the server builds its own collector.
        if isinstance(explain, explaining.ExplainCollector):
            self.explain = explain
        else:
            self.explain = explaining.ExplainCollector(
                enabled=bool(explain), topk=int(explain_k))
        self._explain_k = int(self.explain.topk)
        self.flight.decisions = self.explain
        # Cycle flight ledger (round 18, ISSUE 13): per-cycle telemetry
        # ring + regression sentinel, families in THIS server's metrics
        # registry, anomaly dumps into THIS server's flight recorder /
        # span ring (docstring). Served by the Statusz rpc.
        if ledger is not None:
            self.ledger = ledger
        else:
            self.ledger = ledgering.CycleLedger(
                registry=self.metrics.registry, flight=self.flight,
                tracer=self._trace, jsonl=ledger_jsonl)
        # Wire ledger (round 19, ISSUE 19): the per-cycle round-trip
        # decomposition's home — same discipline as the cycle ledger
        # above (families in THIS registry, anomaly dumps into THIS
        # flight recorder). Clients observe INTO it (wire=svc.wire).
        if wire is not None:
            self.wire = wire
        else:
            self.wire = wiring.WireLedger(
                registry=self.metrics.registry, flight=self.flight,
                tracer=self._trace, profile_dir=wire_profile_dir)
        # Admission-controlled ingest (PR 20, ISSUE 20): token-bucket
        # front door over a device-resident pending queue, served by
        # the Enqueue rpc (docstring above). Gauges/counters land in
        # THIS registry so Metrics renders queue depth and shed rate.
        if ingest is None:
            self.ingest = None
        elif isinstance(ingest, IngestGate):
            self.ingest = ingest
        else:
            spec = dict(ingest) if isinstance(ingest, dict) else {}
            queue = DeviceQueue(
                capacity=int(spec.pop("capacity", 4096)),
                bound=spec.pop("bound", None),
                qos_gain=float(self.config.qos.qos_gain),
            )
            self.ingest = IngestGate(
                queue, faults=self._faults,
                registry=self.metrics.registry, ledger=self.ledger,
                dedup=True, **spec)
        # Live device/store memory surface (ROADMAP item 1 feeds on
        # this): rendered at scrape time from the authoritative maps.
        pm.CallbackGauge(
            "scheduler_device_bytes",
            "live device-resident and host-retained bytes by kind "
            "(session_arrays: per-lineage DeviceSnapshot arrays on "
            "device; byte_stores: registered snapshot byte stores, "
            "shared records counted once per store)",
            ("kind",), callback=self._device_bytes_by_kind,
            registry=self.metrics.registry)
        self._resync_storm = StormDetector(n=4, window_s=5.0)
        self._closed = False
        # Replication (round 11, ISSUE 6): role, the op log, and the
        # takeover/lag surface Health + Metrics export. Appending is
        # unconditional — a standby promoted to leader keeps the same
        # log, whose mirrored ops already carry the old leader's seqs,
        # so a surviving second standby re-follows without a rebase.
        if role not in ("leader", "standby"):
            raise ValueError(f"role={role!r}: want leader|standby")
        self.role = role
        self._role_lock = threading.Lock()
        self._replog = (replication_log if replication_log is not None
                        else ReplicationLog())
        self.takeovers = 0
        self.replication_lag = 0      # updated by StandbyFollower
        self.replication_applied = 0  # ops applied as a standby
        self.replication_skipped = 0  # delta ops whose base was gone
        # Shape-class prewarm (PR 18, ROADMAP item 3): boot-time tracing
        # of the full registry on a daemon thread, so construction stays
        # fast and a fleet boots its replicas' compiles in parallel.
        # prewarm_complete is True for non-prewarming servers too ("as
        # warm as it will get") — wait_caught_up can gate uniformly.
        self.registry = None
        self.registry_classes = 0
        self.prewarm_classes_done = 0
        self.prewarm_s = 0.0
        self.prewarm_error: "str | None" = None
        self.prewarm_complete = not prewarm
        self._prewarm_thread: "threading.Thread | None" = None
        # close() sets this so a boot prewarm racing shutdown abandons
        # its remaining classes after the in-flight compile — a daemon
        # thread left inside XLA at interpreter exit aborts the process.
        self._prewarm_stop = threading.Event()
        if prewarm:
            if self.buckets is None:
                raise ValueError(
                    "prewarm=True needs explicit buckets=: shape classes "
                    "are a function of pinned bucket sizes "
                    "(tpusched.shapeclass.build_registry)"
                )
            self.registry = shapeclass.build_registry(
                self.config, self.buckets,
                explain=self.explain.enabled, explain_k=self._explain_k,
                warm=self._warm,
            )
            self.registry_classes = len(self.registry)
            self._prewarm_thread = threading.Thread(
                target=self._run_prewarm, name="tpusched-prewarm",
                daemon=True)
            self._prewarm_thread.start()

    def _run_prewarm(self) -> None:
        try:
            report = self._engine.prewarm(
                self.registry, should_stop=self._prewarm_stop.is_set)
            if report["cancelled"]:
                logging.getLogger("tpusched.rpc.server").info(
                    "shape-class prewarm cancelled by close() after "
                    "%.2fs", report["prewarm_s"])
                return
            self.prewarm_classes_done = report["classes"]
            self.prewarm_s = report["prewarm_s"]
            self._trace.record(
                "server.prewarm", dur_s=report["prewarm_s"], cat="server",
                classes=report["classes"], compiles=report["compiles"])
        except Exception:
            # A failed prewarm must not wedge wait_caught_up or boot —
            # the server still serves (compiling on demand); the error
            # is loud here and visible via prewarm_error/statusz.
            self.prewarm_error = traceback.format_exc(limit=5)
            logging.getLogger("tpusched.rpc.server").error(
                "shape-class prewarm failed; serving will compile on "
                "demand:\n%s", self.prewarm_error)
        finally:
            self.prewarm_complete = True

    def wait_prewarmed(self, timeout: "float | None" = None) -> bool:
        """Block until the boot prewarm finishes (immediately True when
        prewarm is off). The chaos/bench harnesses call this before
        measuring so cold-start compile time never leaks into serving
        metrics."""
        t = self._prewarm_thread
        if t is not None:
            t.join(timeout)
        return self.prewarm_complete

    def _store_put_locked(self, sid: str, store: SnapshotStore) -> None:
        """Insert + evict under _store_lock (caller holds it). The ONE
        place retention policy lives: the leader's mint path and the
        replication apply path must evict identically or leader/standby
        store retention drifts and the byte-identity contract breaks."""
        self._stores.pop(sid, None)
        self._stores[sid] = store
        self._last_minted = sid
        while len(self._stores) > STORE_CAP:
            self._stores.pop(next(iter(self._stores)))

    def _register_store(self, store: SnapshotStore, op_kind: str = "",
                        payload: bytes = b"", base_id: str = "") -> str:
        """Mint + register; when op_kind is set, the replication-log
        append happens INSIDE the same critical section — op order must
        equal registration order, or the standby's replayed insertion
        (= eviction) order diverges from the leader's under concurrent
        handlers and the two replicas evict different stores."""
        with self._store_lock:
            sid = f"snap-{self._mint_nonce}-{self._next_store}"
            self._next_store += 1
            self._store_put_locked(sid, store)
            if op_kind:
                self._replog.append(op_kind, sid, payload,
                                    base_id=base_id)
        return sid

    def _register_store_as(self, sid: str, store: SnapshotStore) -> None:
        """Register under a LEADER-minted snapshot_id (replication
        apply path). No mint-collision handling needed: local mints
        carry this instance's nonce, so a replicated (other-nonce) sid
        can never alias one we hand out post-takeover."""
        with self._store_lock:
            self._store_put_locked(sid, store)

    # -- replication (round 11) ---------------------------------------------

    def replica_apply(self, op: "pb.ReplicationOp") -> bool:
        """Apply one leader op on a standby: register the op's store
        under the leader's snapshot_id, warm the device session for
        delta lineages, and mirror the op into our own log. Returns
        False (skipped) for a delta op whose base this replica no
        longer holds — safe: the failed-over client heals through
        FAILED_PRECONDITION + full-snapshot resync.

        Runs under _role_lock with a role RE-CHECK: a takeover promotes
        under the same lock, so an apply in flight when a client's
        request promotes us finishes first and every later op is
        refused — an old-leader op delivered post-promotion can never
        overwrite a store the new leader registered. The O(cluster)
        device-session warm-up runs OUTSIDE the lock: a failed-over
        client's promoting request must not wait behind a session
        seed/compile (promotion latency IS failover recovery time;
        warmth is only an optimization)."""
        with self._role_lock:
            if self.role != "standby":
                return False
            applied, warm = self._replica_apply_locked(op)
        if warm is not None:
            self._replica_warm_session(*warm)
        return applied

    def _replica_apply_locked(self, op: "pb.ReplicationOp"):
        """(applied, warm-args-or-None); caller holds _role_lock."""
        with self._trace.span("replica.apply", cat="replica",
                              kind=op.kind, sid=op.snapshot_id) as sp:
            if op.kind == "full":
                msg = pb.ClusterSnapshot.FromString(op.payload)
                store = SnapshotStore()
                store.set_full_bytes(msg)
                self._register_store_as(op.snapshot_id, store)
                warm = None
            elif op.kind == "delta":
                with self._store_lock:
                    base = self._stores.get(op.base_id)
                    if base is not None:
                        # Mirror the serving path's true-LRU hit-touch
                        # of the delta's base: without it, leader and
                        # standby eviction orders diverge past
                        # STORE_CAP and the standby drops exactly the
                        # hot bases a failed-over client will name.
                        self._stores.pop(op.base_id)
                        self._stores[op.base_id] = base
                if base is None:
                    self.replication_skipped += 1
                    self._replog.mirror(op)
                    sp.attrs["skipped"] = True
                    return False, None
                delta = pb.SnapshotDelta.FromString(op.payload)
                store = base.copy()
                store.apply_delta(delta)
                self._register_store_as(op.snapshot_id, store)
                warm = (op.base_id, delta, op.snapshot_id, base)
            else:
                raise ValueError(f"unknown replication op kind {op.kind!r}")
            self._replog.mirror(op)
            self.replication_applied += 1
            return True, warm

    def replica_rebase(self, op: "pb.ReplicationOp") -> None:
        """Full rebase after falling behind log retention: drop every
        store and session (they chain from history we no longer have)
        and start over from the leader's newest store. Same _role_lock
        discipline as replica_apply — a post-promotion rebase must not
        wipe the new leader's stores."""
        with self._role_lock:
            if self.role != "standby":
                return
            with self._store_lock:
                self._stores.clear()
                self._sessions.clear()
            self._replica_apply_locked(op)  # "full" op: no warm-up args

    def _replica_warm_session(self, base_id: str, delta, sid: str,
                              base: SnapshotStore) -> None:
        """Best-effort device-session warm-up on the standby, mirroring
        the leader's lazy-seed-then-apply discipline so a takeover
        starts with the lineage's cluster already ON device. Failures
        drop the warm state silently — the post-takeover decode path is
        always the correctness floor, and a standby must not burn
        ladder demerits for an optimization."""
        if self._session_cap <= 0 or self._ladder.level() != "delta":
            return
        session = None
        try:
            with self._store_lock:
                session = self._sessions.get(base_id)
            if session is None:
                with self._trace.span("session.seed", cat="replica",
                                      base_id=base_id):
                    session = DeviceSession.from_base_store(
                        base, base_id, self.config, self.buckets,
                        mesh=self._mesh,
                    )
                    session.device.tracer = self._trace
                self.session_seeds += 1
            with session.lock:
                session.apply_delta(base_id, delta, sid)  # tpl: disable=TPL102(the apply IS the critical section: the lineage's device state must not advance past the base this op mirrors, and the H2D scatter is the apply itself)
            self._session_put(session)
        except Exception:
            logging.getLogger("tpusched.rpc.server").warning(
                "standby session warm-up failed; takeover will serve "
                "via decode:\n%s", traceback.format_exc(limit=3),
            )
            if session is not None:
                self._drop_session(session)

    def _maybe_takeover(self, rpc: str) -> None:
        """First serving request on a standby: promote to leader. The
        'replica.takeover' fault site can refuse it (split-brain-
        attempt guard) — the caller sees UNAVAILABLE and fails over to
        the next endpoint. The promotion is the failover event, so it
        snapshots the trace ring: the flight dump carries the hand-off
        causal chain (last replication polls + the promoting request)."""
        with self._role_lock:
            if self.role != "standby":
                return
            try:
                self._faults.fire("replica.takeover")  # tpl: disable=TPL102(a takeover delay shot must hold _role_lock: the simulated slow promotion has to block replication applies exactly like a real one would)
            except FaultError as e:
                raise _Abort(
                    grpc.StatusCode.UNAVAILABLE,
                    f"standby refused takeover (split-brain guard): {e}",
                ) from e
            self.role = "leader"
            self.takeovers += 1
            lag = self.replication_lag
            self.replication_lag = 0
        self._trace.record("replica.takeover", cat="replica", rpc=rpc,
                           lag_at_takeover=lag)
        self.flight.record("replica_takeover", self._trace,
                           rpc=rpc, lag_at_takeover=lag)

    @staticmethod
    def _check_delta_upserts(delta) -> None:
        """Defense-in-depth behind DeltaSession's client-side guard: a
        delta upsert with an empty or duplicate name would silently
        collapse in the name-keyed store and solve a corrupted snapshot.
        Reject loudly instead (INVALID_ARGUMENT — retrying the same
        delta cannot succeed, unlike an expired base)."""
        for coll in (delta.upsert_nodes, delta.upsert_pods,
                     delta.upsert_running):
            seen = set()
            for rec in coll:
                if not rec.name or rec.name in seen:
                    raise _Abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "delta upserts must carry unique non-empty names "
                        f"(offending record name: {rec.name!r})",
                    )
                seen.add(rec.name)

    def _session_put(self, session: DeviceSession) -> None:
        """(Re-)register under the session's current keys; LRU-evict
        whole sessions (not keys) past the cap. Sessions stay SHARED in
        the map while requests use them: a depth-2 pipeline always has
        one request in flight, and cumulative-from-pin applies are
        order-independent (every apply restores relative to the pin),
        so concurrent lineage requests serialize on session.lock
        instead of missing and re-seeding."""
        with self._store_lock:
            self._drop_session_locked(session)
            for k in session.keys():
                self._sessions.pop(k, None)
                self._sessions[k] = session
            distinct = []
            for s in self._sessions.values():
                if s not in distinct:
                    distinct.append(s)
            while len(distinct) > max(self._session_cap, 0):
                victim = distinct.pop(0)
                for k in list(self._sessions):
                    if self._sessions[k] is victim:
                        del self._sessions[k]

    def _drop_session_locked(self, session) -> None:
        """Forget every key mapping to `session` (caller holds
        _store_lock) — the single authority for session eviction, so
        the injected-fault paths and the real-failure heal path cannot
        silently diverge."""
        for k in [k for k, v in self._sessions.items() if v is session]:
            del self._sessions[k]

    def _drop_session(self, session) -> None:
        with self._store_lock:
            self._drop_session_locked(session)

    def _device_bytes_by_kind(self) -> dict:
        """Samples for the scheduler_device_bytes gauge (round 12):
        distinct device-resident sessions' array bytes (a session
        registered under two keys counts once) and the registered byte
        stores' retained payload. Only the REFERENCE snapshot happens
        under _store_lock — nbytes() walks O(records) per store, and a
        scrape must not stall the Assign registration path behind that
        walk. Array nbytes is metadata, no D2H."""
        with self._store_lock:
            distinct = []
            for s in self._sessions.values():
                if s not in distinct:
                    distinct.append(s)
            stores = list(self._stores.values())
        store_bytes = sum(st.nbytes() for st in stores)
        dev_bytes = 0
        for s in distinct:
            try:
                dev_bytes += int(s.device.full_bytes)
            except Exception:  # noqa: BLE001 — a scrape must not abort
                continue
        return {("session_arrays",): dev_bytes,
                ("byte_stores",): store_bytes}

    # -- failure-domain helpers (round 8) -----------------------------------

    @staticmethod
    def _replay_key(request) -> "tuple[str, int] | None":
        if not request.HasField("delta"):
            return None
        d = request.delta
        if not d.lineage_id or not d.seq:
            return None
        return (d.lineage_id, int(d.seq))

    def _replay_lookup(self, rpc: str, request):
        """Cached response for a retried (lineage_id, seq), or None."""
        key = self._replay_key(request)
        if key is None:
            return None
        lineage, seq = key
        with self._replay_lock:
            per = self._replay.get(lineage)
            if per is None:
                return None
            resp = per.get((seq, rpc))
            if resp is not None:
                self.replayed_requests += 1
            return resp

    def _replay_record(self, rpc: str, request, resp) -> None:
        key = self._replay_key(request)
        if key is None or resp.ByteSize() > REPLAY_MAX_BYTES:
            return
        lineage, seq = key
        with self._replay_lock:
            per = self._replay.pop(lineage, None)
            if per is None:
                per = {}
            per[(seq, rpc)] = resp
            while len(per) > REPLAY_PER_LINEAGE:
                per.pop(next(iter(per)))
            self._replay[lineage] = per           # LRU refresh
            while len(self._replay) > STORE_CAP:
                self._replay.pop(next(iter(self._replay)))

    def _stage_done(self, stage: str, t0: float) -> None:
        """A stage that ended NOW and started at perf_counter t0: one
        retroactive trace span + the per-stage histogram observation —
        for stages whose start can't be wrapped (gate wait)."""
        dur = time.perf_counter() - t0
        self._trace.record(stage, dur_s=dur, cat="server")
        self.metrics.observe_stage(stage, dur)

    def _join_guarded(self, pending, what: str):
        """Join a device result under the per-dispatch watchdog. A
        timeout converts the hung solve into DEADLINE_EXCEEDED for THIS
        caller, demotes the ladder, and abandons the wedged fetch
        worker so later dispatches get a live one (throttled: N callers
        waiting on the same wedged worker trigger ONE restart)."""
        t0 = time.perf_counter()
        try:
            with self._trace.span("fetch.join", cat="server", what=what):
                res = pending.result(timeout=self.watchdog_s)
            self.metrics.observe_stage("fetch.join",
                                       time.perf_counter() - t0)
            return res
        except _FutTimeout:
            # The hung join IS the long tail the log-scale buckets exist
            # for — it must land in the stage histogram, not only in the
            # trip counter (the success path above can't record it).
            self.metrics.observe_stage("fetch.join",
                                       time.perf_counter() - t0)
            now = time.monotonic()
            with self._watchdog_lock:
                self.watchdog_trips += 1
                restart = now - self._last_worker_restart > 1.0
                if restart:
                    self._last_worker_restart = now
            if restart:
                # One ladder demerit + one worker swap per hang event:
                # N coalesced callers timing out on the SAME wedged
                # dispatch are one device failure, not N — and ONE
                # flight-recorder dump carries the causal trace of the
                # hang (the spans that led to the wedged dispatch).
                self.flight.record("watchdog_trip", self._trace,
                                   what=what, watchdog_s=self.watchdog_s)
                self._device_failure()
                self._engine.restart_fetch_worker()
            raise _Abort(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                f"{what} result did not land within the "
                f"{self.watchdog_s:.1f}s dispatch watchdog; fetch worker "
                "restarted and the device fast path demoted — the server "
                "keeps serving, re-submit as a new cycle",
            )

    def _device_failure(self, demote_from_delta: bool = True) -> None:
        """Ladder bookkeeping for a device-path failure; on demotion
        out of 'delta', drop resident sessions (their device arrays are
        the state under suspicion, and the memory buys nothing while
        quarantined). Every demotion snapshots the trace ring: the
        operator gets the spans that spent the ladder's patience, not
        just a counter bump."""
        demoted = self._ladder.record_failure()
        if demoted and demote_from_delta:
            with self._store_lock:
                self._sessions.clear()
        if demoted:
            self.flight.record("ladder_demotion", self._trace,
                               level=self._ladder.snapshot()["level"])

    def _resolve_decoded(self, request):
        """Full-or-delta request -> (snap, meta, snapshot_id,
        decode_seconds, device_stats|None, device_session|None) with
        the decoded arrays ready for dispatch; the trailing session is
        non-None exactly when the delta applied through a live
        DeviceSession (the warm-solve routing hook, round 17).

        Delta requests against a lineage with a live DeviceSession skip
        the recompose + full decode + full H2D entirely: the delta
        applies as on-device scatter updates (O(churn) host work) and
        `device_stats` reports what was shipped. The byte store is
        still advanced and registered either way — it is the fallback
        truth for forks, session eviction, and seeding.

        Unknown/expired base_id raises _Abort(FAILED_PRECONDITION) so
        the client falls back to a full snapshot (DeltaSession does).
        Snapshots whose records lack unique non-empty names are served
        but not registered (empty snapshot_id): name-keyed stores would
        collapse them (DeltaSession refuses to delta against those too).

        Degradation (round 8): at the 'rebuild' rung device sessions
        are skipped entirely (every delta recomposes + re-decodes); at
        'stateless' deltas are refused with FAILED_PRECONDITION and
        full sends are served WITHOUT registering a store (empty
        snapshot_id), so clients settle into full-send-per-cycle
        instead of ping-ponging delta attempts off a refusing server.
        """
        self._faults.fire("server.decode")
        level = self._ladder.level()
        if request.HasField("delta"):
            if level == "stateless":
                raise _Abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    "sidecar degraded to stateless serving "
                    "(degradation ladder); resend a full snapshot",
                )
            base_id = request.delta.base_id
            if not base_id:
                # Falling through would silently solve the empty default
                # snapshot; a delta without a base cannot be resolved.
                raise _Abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "delta request carries no base_id",
                )
            self._check_delta_upserts(request.delta)
            with self._store_lock:
                base = self._stores.get(base_id)
                if base is not None:
                    # True-LRU refresh: a hit keeps the base alive while
                    # unrelated sessions churn the cap.
                    self._stores.pop(base_id)
                    self._stores[base_id] = base
            if base is None:
                raise _Abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"unknown snapshot base_id {base_id!r}",
                )
            store = base.copy()
            store.apply_delta(request.delta)
            # Replication op (round 11): ship the delta verbatim; the
            # standby re-applies it against its own copy of base_id and
            # registers the result under this very sid.
            sid = self._register_store(
                store, "delta", request.delta.SerializeToString(),
                base_id=base_id,
            )
            t0 = time.perf_counter()
            seeding = False
            session = None
            with self._store_lock:
                # The 'rebuild' rung quarantines the device-resident
                # path: no lookups, no seeding — pure decode serving.
                if level == "delta":
                    session = self._sessions.get(base_id)
                    if (session is None and self._session_cap > 0
                            and base_id not in self._seeding):
                        self._seeding.add(base_id)
                        seeding = True
            if seeding:
                # Lazy seed on the FIRST delta of a lineage, from the
                # BASE store (so the pin matches what pipelined clients
                # keep diffing against): one O(cluster) record
                # conversion + build + upload buys O(churn) host work
                # for every later delta. Full-send-only clients never
                # pay this; a concurrent second first-delta skips the
                # duplicate build (_seeding guard) and decodes.
                try:
                    with self._trace.span("session.seed", cat="server",
                                          base_id=base_id):
                        session = DeviceSession.from_base_store(
                            base, base_id, self.config, self.buckets,
                            mesh=self._mesh,
                        )
                        session.device.tracer = self._trace
                    self.session_seeds += 1
                except Exception:
                    logging.getLogger("tpusched.rpc.server").warning(
                        "device session seed failed; serving via the "
                        "decode path:\n%s", traceback.format_exc(limit=3),
                    )
                    self._device_failure()
                finally:
                    with self._store_lock:
                        self._seeding.discard(base_id)
            if session is not None:
                try:
                    shot = self._faults.fire("server.session")
                except FaultError:
                    # Injected apply-path failure: same handling as a
                    # real session exception — drop the lineage, demote
                    # the ladder, heal through decode.
                    self._drop_session(session)
                    self._device_failure()
                    session = None
                else:
                    if shot == "drop":
                        # Injected eviction (chaos: DeviceSession LRU
                        # pressure / store-cap fork): forget the
                        # lineage; this request and the lineage's next
                        # delta heal through decode + re-seed — no
                        # ladder demerit, eviction is a normal event.
                        self._drop_session(session)
                        session = None
            if session is not None:
                try:
                    with session.lock:
                        t_a = time.perf_counter()
                        with self._trace.span("delta.apply",
                                              cat="server") as sp:
                            stats = session.apply_delta(  # tpl: disable=TPL102(the apply IS the critical section: a concurrent apply moving the lineage past this request's base must fork, not interleave, and the H2D scatter is the apply itself)
                                base_id, request.delta, sid)
                            sp.attrs.update(h2d_bytes=stats.h2d_bytes,
                                            path=stats.path)
                        apply_s = time.perf_counter() - t_a
                        snap, meta = session.device.snap, session.device.meta
                except KeyError:
                    # Expected fork: the lineage moved past this base
                    # while we waited. Serve via decode; the session is
                    # untouched. (Counted below as a miss, not a hit.)
                    pass
                except Exception:
                    # Heal through the decode path; the session may be
                    # inconsistent, so drop it (loud, like the native-
                    # decoder fallback: silent means a permanent
                    # O(cluster) regression).
                    logging.getLogger("tpusched.rpc.server").warning(
                        "device session apply failed; dropping the "
                        "lineage and re-decoding:\n%s",
                        traceback.format_exc(limit=3),
                    )
                    self._drop_session(session)
                    # Ladder bookkeeping: repeated apply failures
                    # quarantine the whole device-resident path.
                    self._device_failure()
                else:
                    self._session_put(session)
                    self.metrics.observe_stage("delta.apply", apply_s)
                    self.metrics.h2d.labels(stats.path).observe(
                        stats.h2d_bytes)
                    if not seeding:
                        # Counted on SUCCESS only, so a fork's KeyError
                        # (hit-then-decode) is one miss, not hit+miss —
                        # hits + seeds + misses == delta requests.
                        self.session_hits += 1
                    return (snap, meta, sid, time.perf_counter() - t0, stats,
                            session)
            self.session_misses += 1
            # Bytes composition straight into the (native) decoder: no
            # Python ClusterSnapshot is materialized on the delta path.
            with self._trace.span("store.compose", cat="server"):
                raw = store.compose_bytes()
            snap, meta, decode_s = self._decode(raw)
            return snap, meta, sid, decode_s, None, None
        msg = request.snapshot
        if not delta_safe(msg) or level == "stateless":
            snap, meta, decode_s = self._decode(msg)
            return snap, meta, "", decode_s, None, None
        store = SnapshotStore()
        # One serialize pass per record at full-send time so every
        # later delta cycle serializes only its churn (apply_delta) and
        # composes by concatenation.
        store.set_full_bytes(msg)
        sid = self._register_store(store, "full", msg.SerializeToString())
        snap, meta, decode_s = self._decode(msg)
        return snap, meta, sid, decode_s, None, None

    def _decode(self, snapshot_msg):
        t0 = time.perf_counter()
        with self._trace.span("decode", cat="server") as sp:
            snap, meta = decode_snapshot(
                snapshot_msg, self.config, self.buckets
            )
            sp.attrs.update(pods=meta.n_pods, nodes=meta.n_nodes)
        decode_s = time.perf_counter() - t0
        self.metrics.observe_stage("decode", decode_s)
        return snap, meta, decode_s

    def close(self) -> None:
        """Release serving resources: refuse queued dispatches, drain
        the engine's fetch worker (in-flight results complete), drop
        device-resident sessions and the replay cache. Idempotent and
        safe to race with in-flight handlers or a concurrent close
        (every step below is itself re-entrant); call after
        server.stop()."""
        with self._store_lock:
            already = self._closed
            self._closed = True
        # Stop a still-running boot prewarm FIRST (before the engine's
        # fetch worker drains — prewarm dispatches through it): it
        # abandons remaining classes after its in-flight compile. The
        # bounded join keeps close() from hanging on a pathological
        # compile; the thread is a daemon either way.
        self._prewarm_stop.set()
        t = self._prewarm_thread
        if t is not None:
            t.join(timeout=60.0)
        self._gate.close()
        self._engine.close(wait=True)
        self.ledger.close()  # releases the JSONL black box, if any
        self.wire.close()
        with self._store_lock:
            self._sessions.clear()
        if not already:
            with self._replay_lock:
                self._replay.clear()

    def _log_batch(self, rpc: str, meta, decode_s: float, solve_s: float,
                   placed: int, evicted: int, rounds: int,
                   dstats=None, fused: int = 0):
        rec = dict(
            ts=time.time(), rpc=rpc, pods=meta.n_pods, nodes=meta.n_nodes,
            running=meta.n_running, buckets=[meta.buckets.pods, meta.buckets.nodes],
            decode_s=round(decode_s, 6), solve_s=round(solve_s, 6),
            placed=placed, evicted=evicted, rounds=rounds,
            placements_per_sec=round(placed / solve_s, 1) if solve_s > 0 else 0,
        )
        if dstats is not None:
            rec["device_path"] = dstats.path
            rec["h2d_bytes"] = dstats.h2d_bytes
            if dstats.reason:
                rec["device_rebuild_reason"] = dstats.reason
        if fused:
            rec["fused"] = fused
        print(json.dumps(rec), file=self._log, flush=True)

    # -- rpc methods --------------------------------------------------------

    @staticmethod
    def _peer(context) -> str:
        """Gate client identity; in-process callers (tests invoking
        handlers directly) have no grpc context."""
        return context.peer() if context is not None else "in-process"

    @staticmethod
    def _score_key(request: pb.ScoreRequest):
        """Coalescing identity of a ScoreBatch DELTA request: same base
        + byte-identical delta = identical post-delta cluster state.
        Full sends never coalesce (hashing the whole snapshot would
        cost more than it saves), and the form kind separates top-k
        fusions (k merged) from full-matrix fusions (exact dedupe).
        lineage_id/seq are retry bookkeeping, NOT cluster state — they
        are scrubbed before hashing so identical deltas from distinct
        client lineages still fuse."""
        if not request.HasField("delta"):
            return None
        kind = ("topk" if request.top_k > 0
                else f"full-packed{int(bool(request.packed_ok))}")
        d = request.delta
        if d.lineage_id or d.seq:
            scrub = pb.SnapshotDelta()
            scrub.CopyFrom(d)
            scrub.lineage_id = ""
            scrub.seq = 0
            d = scrub
        digest = hashlib.sha1(d.SerializeToString()).hexdigest()
        return (request.delta.base_id, digest, kind)

    @staticmethod
    def _abort(context, code, details):
        """context.abort, or the raw status as an exception for
        in-process callers (context=None — see _peer)."""
        if context is None:
            raise _Abort(code, details)
        context.abort(code, details)

    def _serve(self, rpc: str, request, context, inner):
        """Shared outermost handler path: one trace root span per
        request (rooted at the wire request_id/parent_span; absent id
        => server-minted), replay dedupe, outcome counting by final
        status code, taxonomy conversion, and the flight-recorder
        resync-storm trigger. Aborts raise THROUGH the span, which
        records the error attr on the way out."""
        rid = request.request_id or self._trace.new_trace_id()
        with self._trace.request(rid, int(request.parent_span),
                                 name=f"server.{rpc}", cat="server",
                                 peer=self._peer(context)) as root:
            replay = self._replay_lookup(rpc, request)
            if replay is not None:
                root.attrs["replayed"] = True
                self.metrics.count_request(rpc, "OK")
                self._count_wire_bytes(rpc, request, replay)
                return replay
            try:
                # A serving request reaching a standby IS the failover
                # signal: promote (or refuse — split-brain guard site).
                self._maybe_takeover(rpc)
                resp = inner(request, context)
                # Chaos site for the reply path (round 19): a delay
                # here stalls the response AFTER every server stage
                # completed — the injected wire stall the wire
                # sentinel must attribute to "transfer".
                self._faults.fire("server.reply")
            except _Abort as e:
                self._count_abort(rpc, e.code, root)
                self._abort(context, e.code, e.details)
            except _Overloaded as e:
                self.metrics.overloaded.labels(rpc).inc()
                self._count_abort(rpc, grpc.StatusCode.RESOURCE_EXHAUSTED,
                                  root)
                self._abort(context, grpc.StatusCode.RESOURCE_EXHAUSTED,
                            str(e))
            except Exception as e:  # taxonomy: fatal (a bug, not a retry)
                self._log_internal(rpc, e)
                self._count_abort(rpc, grpc.StatusCode.INTERNAL, root)
                self._abort(context, grpc.StatusCode.INTERNAL,
                            f"unexpected server error: "
                            f"{type(e).__name__}: {e}")
            else:
                self.metrics.count_request(rpc, "OK")
                self._replay_record(rpc, request, resp)
                self._record_ladder_success(request)
                self._count_wire_bytes(rpc, request, resp)
                return resp

    def _count_wire_bytes(self, rpc: str, request, resp) -> None:
        """Per-direction byte accounting at the serving boundary
        (round 19, ISSUE 19): serialized request bytes up, serialized
        reply bytes down, plus the reply-size histogram. ByteSize() is
        the serialized length protobuf already computed (cached) for
        the transport — no second serialization."""
        down = resp.ByteSize()
        self.metrics.wire_bytes.labels("up", rpc).inc(request.ByteSize())
        self.metrics.wire_bytes.labels("down", rpc).inc(down)
        self.metrics.reply_bytes.labels(rpc).observe(down)

    def _count_abort(self, rpc: str, code, root) -> None:
        name = getattr(code, "name", str(code))
        self.metrics.count_request(rpc, name)
        root.attrs["code"] = name
        if code == grpc.StatusCode.FAILED_PRECONDITION:
            self.metrics.resyncs.labels(rpc).inc()
            if self._resync_storm.hit():
                # A resync STORM (every client re-pinning at once —
                # restart fallout, ladder stateless, LRU thrash) gets a
                # causal dump, not just per-request errors.
                self.flight.record(
                    "resync_storm", self._trace, rpc=rpc,
                    n=self._resync_storm.n,
                    window_s=self._resync_storm.window_s,
                )

    def ScoreBatch(self, request: pb.ScoreRequest, context) -> pb.ScoreResponse:
        # maybe_profile: a no-op unless the PREVIOUS cycle's wire
        # anomaly armed a one-shot jax.profiler device-trace capture
        # (WireLedger docstring) — two attribute reads when unarmed.
        with self.wire.maybe_profile():
            return self._serve("ScoreBatch", request, context,
                               self._score_batch)

    def _score_batch(self, request: pb.ScoreRequest, context) -> pb.ScoreResponse:
        key = self._score_key(request)
        fusion = None
        if key is not None:
            fusion, leader = self._coalescer.join(key, int(request.top_k))
            if not leader:
                # A leader is already resolving this exact state: wait
                # for its dispatch and slice our own k from the shared
                # result — no decode, no dispatch, no extra fetch.
                with self._trace.span("coalesce.wait", cat="server"):
                    payload = fusion.wait(timeout=600.0)
                resp, solve_s = self._score_response(payload, request)
                self.metrics.observe(payload["P"], 0, 0, solve_s,
                                     rpc="ScoreBatch")
                return resp
        try:
            payload = self._score_dispatch(request, context, fusion)
        except BaseException as e:
            if fusion is not None:
                # Followers must see the SAME status class the leader
                # got — an _Overloaded leader means the whole fusion was
                # refused admission (retryable), not a server bug.
                if isinstance(e, _Abort):
                    code = e.code
                elif isinstance(e, _Overloaded):
                    code = grpc.StatusCode.RESOURCE_EXHAUSTED
                else:
                    code = grpc.StatusCode.INTERNAL
                fusion.fail(code, str(e))
                self._coalescer.finish(fusion)
            raise
        if fusion is not None:
            fusion.publish(payload)
            self._coalescer.finish(fusion)
            self.metrics.fuse.observe(len(fusion._ks))
        resp, solve_s = self._score_response(payload, request)
        self._log_batch(
            "ScoreBatch", payload["meta"], payload["decode_s"], solve_s,
            0, 0, 0, dstats=payload["dstats"],
            fused=(len(fusion._ks) - 1) if fusion is not None else 0,
        )
        self.metrics.observe(payload["P"], 0, 0, payload["decode_s"] + solve_s,
                             rpc="ScoreBatch")
        return resp

    def _score_dispatch(self, request, context, fusion) -> dict:
        """Leader path: resolve + decode outside the dispatch slot,
        dispatch the requested form once (k = fused max for top-k),
        return the shared payload followers slice from."""
        snap, meta, sid, decode_s, dstats, session = \
            self._resolve_decoded(request)
        P, N = meta.n_pods, meta.n_nodes
        pending_topk = pending_full = None
        k_used = 0
        t_q = time.perf_counter()
        with self._gate.slot(self._peer(context)):
            self._stage_done("gate.wait", t_q)
            # Seal INSIDE the slot: every request that joined while this
            # one queued rides the same dispatch.
            k_fused = fusion.seal() if fusion is not None \
                else int(request.top_k)
            with self._trace.span("dispatch", cat="server",
                                  fused=len(fusion._ks) if fusion else 1):
                if request.top_k > 0:
                    # O(P) response: top-k computed on device, [P,N]
                    # never fetched. A drained cluster (N == 0) has
                    # nothing to rank: k stays 0 with no rows, which
                    # the client decodes as [P, 0] arrays.
                    if N > 0:
                        k_used = min(max(k_fused, 1), N)
                        pending_topk = self._engine.score_topk_async(
                            snap, k_used)
                else:
                    pending_full = self._engine.score_async(snap)
        return dict(sid=sid, meta=meta, P=P, N=N, decode_s=decode_s,
                    dstats=dstats, k_used=k_used,
                    pending_topk=pending_topk, pending_full=pending_full)

    def _score_response(self, payload: dict, request) -> tuple[pb.ScoreResponse, float]:
        """Build ONE caller's response from the (possibly shared)
        payload: name tables now — they ride inside the device window —
        then join the fetch (watchdog-guarded) and pack this caller's
        k columns."""
        meta = payload["meta"]
        P, N = payload["P"], payload["N"]
        resp = pb.ScoreResponse(snapshot_id=payload["sid"])
        with self._trace.span("reply.names", cat="server"):
            resp.pod_names.extend(meta.pod_names)
            resp.node_names.extend(meta.node_names)
        solve_s = 0.0
        t_p = None
        if payload["pending_topk"] is not None:
            idx, val, solve_s = self._join_guarded(
                payload["pending_topk"], "ScoreBatch top-k"
            )
            t_p = time.perf_counter()
            with self._trace.span("reply.pack", cat="server"):
                # lax.top_k is prefix-stable: columns [:k_own] of the
                # fused top-k_used equal a direct top-k_own dispatch, so
                # sliced responses are byte-identical to unfused serving.
                k_own = min(int(request.top_k), N)
                resp.k = k_own
                resp.topk_idx_packed = np.ascontiguousarray(
                    idx[:P, :k_own], dtype="<i4"
                ).tobytes()
                resp.topk_score_packed = np.ascontiguousarray(
                    val[:P, :k_own], dtype="<f4"
                ).tobytes()
        elif payload["pending_full"] is not None:
            res = self._join_guarded(payload["pending_full"],
                                     "ScoreBatch full")
            solve_s = res.solve_seconds
            t_p = time.perf_counter()
            with self._trace.span("reply.pack", cat="server"):
                if request.packed_ok and P * N >= PACK_CELLS:
                    resp.feasible_packed = np.ascontiguousarray(
                        res.feasible[:P, :N], dtype=np.uint8
                    ).tobytes()
                    resp.scores_packed = np.ascontiguousarray(
                        res.scores[:P, :N], dtype="<f4"
                    ).tobytes()
                else:
                    for i in range(P):
                        row = resp.rows.add()
                        row.feasible.extend(res.feasible[i, :N].tolist())
                        row.scores.extend(res.scores[i, :N].tolist())
        if t_p is not None:
            self.metrics.observe_stage("reply.pack",
                                       time.perf_counter() - t_p)
        return resp, solve_s

    def Assign(self, request: pb.AssignRequest, context) -> pb.AssignResponse:
        # See ScoreBatch: one-shot armed device-trace capture site.
        with self.wire.maybe_profile():
            return self._serve("Assign", request, context, self._assign)

    def _record_ladder_success(self, request) -> None:
        """Probe discipline: a success arms/confirms recovery only when
        it exercised the CURRENT rung's serving path. Delta requests do
        (device sessions at 'delta', store+decode at 'rebuild'); full
        sends are rubber stamps at those rungs and must not clear a
        probation the probe never tested — but at 'stateless' full
        sends ARE the serving path (deltas are refused), so they count
        there, or the ladder could never climb back."""
        if request.HasField("delta") or self._ladder.level() == "stateless":
            self._ladder.record_success()

    @staticmethod
    def _log_internal(rpc: str, exc: BaseException) -> None:
        logging.getLogger("tpusched.rpc.server").error(
            "%s failed unexpectedly (INTERNAL):\n%s",
            rpc, traceback.format_exc(limit=5),
        )

    def _assign(self, request: pb.AssignRequest, context) -> pb.AssignResponse:
        # Flight-ledger context (round 18, ISSUE 13): compile counters
        # BEFORE any decode/dispatch so the record attributes exactly
        # the retraces this request paid; churn is the delta's own
        # record count (0 for full sends — a full send is a reload,
        # not churn).
        comp0 = (ledgering.COMPILES.counters()
                 if self.ledger.enabled else (0, 0.0))
        churn = 0
        if request.HasField("delta"):
            d = request.delta
            churn = (len(d.upsert_nodes) + len(d.remove_nodes)
                     + len(d.upsert_pods) + len(d.remove_pods)
                     + len(d.upsert_running) + len(d.remove_running))
        snap, meta, sid, decode_s, dstats, session = \
            self._resolve_decoded(request)
        # Staged handling (round 6): decode runs OUTSIDE the dispatch
        # slot (so a concurrent request's decode overlaps this solve),
        # the slot is held only long enough to enqueue the program, and
        # the response's name tables build while the engine's worker
        # drives the device and fetches the packed buffer. The gate
        # (round 7) additionally keeps concurrent clients' dispatches
        # round-robin fair instead of lock-race ordered.
        explain_on = self.explain.enabled
        pending_probe = None
        warm_path = "cold"
        t_q = time.perf_counter()
        with self._gate.slot(self._peer(context)):
            self._stage_done("gate.wait", t_q)
            with self._trace.span("dispatch", cat="server",
                                  explained=explain_on):
                pending = None
                if explain_on:
                    # Explained cycle (round 12): the solve carries the
                    # provenance extras and a second program decomposes
                    # scores/filters — both fetch on the ordered worker.
                    pending, pending_probe = (
                        self._engine.solve_explained_async(
                            snap, self._explain_k))
                elif self._warm is not None and session is not None:
                    # Warm routing (round 17, ISSUE 12): the delta
                    # already applied on this lineage's DeviceSnapshot,
                    # so the carried tableau (and, incrementally, the
                    # assignment carry) is one dirty-row refresh away.
                    # Under session.lock: dispatch must see the exact
                    # state this request's delta produced — a
                    # concurrent apply having moved the lineage past it
                    # falls back to the plain solve of OUR decoded
                    # arrays (same heal as a fork).
                    try:
                        with session.lock:
                            if session.device.snap is snap:
                                dev = session.device
                                before = (dev.warm_solves,
                                          dev.incremental_solves)
                                pending = self._engine.solve_warm_async(
                                    dev,
                                    incremental=(
                                        self._warm == "incremental"),
                                )
                                if dev.warm_solves > before[0]:
                                    warm_path = "bitwise"
                                elif dev.incremental_solves > before[1]:
                                    warm_path = "incremental"
                    except Exception:
                        # The warm path is an optimization: any failure
                        # heals through the plain solve (loud — silent
                        # means a permanent round-count regression).
                        logging.getLogger("tpusched.rpc.server").warning(
                            "warm solve dispatch failed; serving via "
                            "the plain solve:\n%s",
                            traceback.format_exc(limit=3),
                        )
                        pending = None
                        warm_path = "cold"
                if pending is None and not explain_on:
                    pending = self._engine.solve_async(snap)
        resp = pb.AssignResponse(snapshot_id=sid)
        P = meta.n_pods
        if request.packed_ok:
            # Name tables now, result arrays after the join: the two
            # string extends are the response's CPU-heavy part at 10k
            # pods and ride inside the device window for free.
            with self._trace.span("reply.names", cat="server"):
                resp.pod_names.extend(meta.pod_names)
                # Indices resolve against the DECODER's canonical
                # (sorted) node order, not the request's wire order —
                # ship the table.
                resp.node_names.extend(meta.node_names)
        exd = None
        try:
            if explain_on:
                res, exd = self._join_guarded(pending, "Assign solve")
            else:
                res = self._join_guarded(pending, "Assign solve")
        except BaseException:
            if warm_path != "cold" and session is not None:
                # The conservative reset the warm contract demands: a
                # dispatch whose FETCH failed may have committed a
                # tableau/carry the device never validated — drop them
                # so the lineage's next solve re-anchors cold instead
                # of repeating a poisoned warm state every request.
                session.device.invalidate_warm("fetch_error")
            raise
        t_p = time.perf_counter()
        with self._trace.span("reply.pack", cat="server"):
            ni = np.asarray(res.assignment[:P], dtype=np.int32)
            sc = np.asarray(res.chosen_score[:P], dtype=np.float32).copy()
            sc[~np.isfinite(sc)] = 0.0  # -inf (unplaced/preempted) -> 0
            ck = np.asarray(res.commit_key[:P], dtype=np.int32)
            placed = int((ni >= 0).sum())
            if request.packed_ok:
                # Parallel-array form: three tobytes() instead of P
                # Python message constructions (~30 ms saved at 10k).
                resp.node_idx_packed = ni.astype("<i4").tobytes()
                resp.score_packed = sc.astype("<f4").tobytes()
                resp.commit_key_packed = ck.astype("<i4").tobytes()
            else:
                for i, name in enumerate(meta.pod_names):
                    a = resp.assignments.add()
                    a.pod = name
                    n = int(ni[i])
                    if n >= 0:
                        a.node = meta.node_names[n]
                        a.score = float(sc[i])
                    a.commit_key = int(ck[i])
        self.metrics.observe_stage("reply.pack", time.perf_counter() - t_p)
        n_evicted = 0
        if res.evicted is not None and res.evicted.any():
            running_names = getattr(meta, "running_names", None) or []
            for m in np.argwhere(res.evicted).ravel():
                if m < len(running_names):
                    resp.evicted.append(running_names[m])
                    n_evicted += 1
        if self._audit is not None:
            ts = time.time()
            lines = []
            for i, name in enumerate(meta.pod_names):
                n = int(ni[i])
                lines.append(json.dumps(dict(
                    ts=ts, kind="placement", pod=name,
                    node=meta.node_names[n] if n >= 0 else None,
                    score=round(float(sc[i]), 4),
                    commit_key=int(ck[i]), snapshot_id=sid,
                )))
            for name in resp.evicted:
                lines.append(json.dumps(dict(
                    ts=ts, kind="eviction", pod=name, snapshot_id=sid,
                )))
            # One write per batch under a lock: concurrent handlers must
            # not interleave partial lines into the audit log.
            if lines:
                with self._audit_lock:
                    self._audit.write("\n".join(lines) + "\n")
                    self._audit.flush()
        if explain_on:
            # BEST-EFFORT: the reply is already complete — a failed or
            # wedged provenance probe must not fail a served placement
            # (no _join_guarded here: a trip would also demote the
            # ladder and abandon the fetch worker for an observability-
            # only program). The plain result(timeout=) converts a hang
            # into a skipped record instead.
            try:
                probe = pending_probe.result(timeout=self.watchdog_s)
            except Exception:  # noqa: BLE001 — observability best-effort
                logging.getLogger("tpusched.rpc.server").warning(
                    "explain probe failed; skipping the decision "
                    "record:\n%s", traceback.format_exc(limit=3),
                )
                probe = None
            if probe is not None:
                ctx = self._trace.current()
                rec = explaining.build_record(
                    self.config, meta, res, exd, probe,
                    rid=ctx[0] if ctx else "", snapshot_id=sid,
                    rpc="Assign",
                )
                cyc = self.explain.record(rec)
                # One "decision" event span under the request root: the
                # Perfetto export's args then link the slow cycle to its
                # DecisionRecord by cycle id (tools/tracez.py satellite).
                self._trace.record("decision", cat="explain",
                                   decision=cyc, pods=meta.n_pods,
                                   evictions=n_evicted)
                for oc, n in explaining.outcome_counts(rec).items():
                    if n:
                        self.metrics.decisions.labels(oc).inc(n)
                for reason, n in explaining.pending_reasons(rec).items():
                    if n:
                        self.metrics.pending_reasons.labels(reason).inc(n)
        resp.rounds = res.rounds
        resp.solve_seconds = res.solve_seconds
        self._log_batch("Assign", meta, decode_s, res.solve_seconds,
                        placed, n_evicted, res.rounds, dstats=dstats)
        self.metrics.observe(meta.n_pods, placed, n_evicted,
                             decode_s + res.solve_seconds)
        self.metrics.solve_rounds.observe(res.rounds)
        self.metrics.warm_solves.labels(warm_path).inc()
        # One flight-ledger record per served Assign (round 18, ISSUE
        # 13): stage walls joined from this request's completed spans
        # (same names a trace shows — decode, delta.apply, dispatch,
        # fetch.join, reply.*), falling back to the directly measured
        # walls when tracing is off. The sentinel inside observe()
        # flags p99 spikes and attributes them from the record itself.
        if self.ledger.enabled:
            c1, s1 = ledgering.COMPILES.counters()
            ctx = self._trace.current()
            stages = self._trace.durations(ctx[0]) if ctx else {}
            if not stages:
                stages = {"decode": decode_s,
                          "fetch.join": res.solve_seconds}
            frontier = 0
            if res.inc_info:
                frontier = int(res.inc_info.get("frontier", 0))
            self.ledger.observe(ledgering.CycleRecord(
                ts=time.time(), source="sidecar", pods=meta.n_pods,
                nodes=meta.n_nodes, running=meta.n_running,
                placed=placed, evicted=n_evicted, churn=churn,
                frontier=frontier, rounds=int(res.rounds),
                # The ledger schema's canonical spelling is "warm"
                # (cold|warm|incremental); the warm-solves counter
                # keeps its historical "bitwise" label.
                warm_path=("warm" if warm_path == "bitwise"
                           else warm_path),
                solve_s=res.solve_seconds,
                stages=stages, compiles=c1 - comp0[0],
                compile_s=round(s1 - comp0[1], 6),
            ))
        return resp

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        """Liveness + the failure-domain surface a sidecar watchdog
        (liveness probe, chaos harness, operator) reads: which ladder
        rung is serving, the trip/demotion/recovery/replay counters,
        and (round 11) the replication role / lag / takeover count."""
        lad = self._ladder.snapshot()
        return pb.HealthResponse(
            ok=True, backend=jax.default_backend(),
            devices=len(jax.devices()),
            serving_path=lad["level"],
            watchdog_trips=self.watchdog_trips,
            ladder_demotions=lad["demotions"],
            ladder_recoveries=lad["recoveries"],
            replayed_requests=self.replayed_requests,
            role=self.role,
            replication_lag_seq=self.replication_lag,
            takeovers=self.takeovers,
            prewarm_complete=self.prewarm_complete,
        )

    def Replicate(self, request: pb.ReplicateRequest,
                  context) -> pb.ReplicateResponse:
        """Serve the op log to a follower (round 11). A from_seq that
        predates retention gets resync=true + ONE full-rebase op built
        from the newest registered store (the follower drops its state
        and resumes from end_seq + 1); a caught-up follower gets an
        empty ops list and the current end_seq as its lag reference."""
        ops, end, stale = self._replog.since(int(request.from_seq))
        resp = pb.ReplicateResponse(end_seq=end, resync=stale,
                                    role=self.role)
        if stale:
            with self._store_lock:
                # Newest REGISTERED store — not dict order: the delta
                # serving path's true-LRU hit-touch moves old bases to
                # the end of _stores, and a rebase op built from one of
                # those but stamped seq=end would leave the follower
                # "caught up" on stale state.
                newest = (self._last_minted
                          if self._last_minted in self._stores
                          else next(reversed(self._stores), None))  # tpl: disable=TPL007(deliberate: _last_minted was evicted, so most-recently-TOUCHED is the freshest state a follower can rebase onto)
                store = self._stores.get(newest) if newest else None
            if store is not None:
                op = resp.ops.add()
                op.seq = end
                op.kind = "full"
                op.snapshot_id = newest
                op.payload = store.compose_bytes()
        else:
            resp.ops.extend(ops)
        return resp

    def Metrics(self, request: pb.MetricsRequest, context) -> pb.MetricsResponse:
        lad = self._ladder.snapshot()
        level_idx = DegradationLadder.LEVELS.index(lad["level"])
        # Live service-state families rendered at scrape time (the
        # registry holds observation-fed metrics; these read the
        # authoritative in-memory counters directly).
        extra = [
            "# TYPE scheduler_watchdog_trips_total counter",
            f"scheduler_watchdog_trips_total {self.watchdog_trips}",
            "# TYPE scheduler_ladder_demotions_total counter",
            f"scheduler_ladder_demotions_total {lad['demotions']}",
            "# TYPE scheduler_ladder_recoveries_total counter",
            f"scheduler_ladder_recoveries_total {lad['recoveries']}",
            "# TYPE scheduler_replayed_requests_total counter",
            f"scheduler_replayed_requests_total {self.replayed_requests}",
            "# TYPE scheduler_degradation_level gauge",
            f'scheduler_degradation_level{{path="{lad["level"]}"}} '
            f"{level_idx}",
            "# TYPE scheduler_device_session_events_total counter",
            f'scheduler_device_session_events_total{{event="seed"}} '
            f"{self.session_seeds}",
            f'scheduler_device_session_events_total{{event="hit"}} '
            f"{self.session_hits}",
            f'scheduler_device_session_events_total{{event="miss"}} '
            f"{self.session_misses}",
            "# TYPE scheduler_gate_served_total counter",
            f"scheduler_gate_served_total {self._gate.served}",
            "# TYPE scheduler_gate_peak_waiting gauge",
            f"scheduler_gate_peak_waiting {self._gate.peak_waiting}",
            "# TYPE scheduler_coalesced_requests_total counter",
            f'scheduler_coalesced_requests_total{{role="leader"}} '
            f"{self._coalescer.lead_requests}",
            f'scheduler_coalesced_requests_total{{role="follower"}} '
            f"{self._coalescer.fused_requests}",
            "# TYPE scheduler_flight_dumps_total counter",
            f"scheduler_flight_dumps_total {self.flight.trips}",
            # Replication surface (round 11, ISSUE 6): role as a
            # labeled gauge (value 1 on the current role), lag in ops,
            # takeovers, and the op-log flow counters.
            "# TYPE scheduler_replica_role gauge",
            f'scheduler_replica_role{{role="{self.role}"}} 1',
            "# TYPE scheduler_replication_lag_seq gauge",
            f"scheduler_replication_lag_seq {self.replication_lag}",
            "# TYPE scheduler_replica_takeovers_total counter",
            f"scheduler_replica_takeovers_total {self.takeovers}",
            "# TYPE scheduler_replication_ops_total counter",
            f'scheduler_replication_ops_total{{op="appended"}} '
            f"{self._replog.appended}",
            f'scheduler_replication_ops_total{{op="applied"}} '
            f"{self.replication_applied}",
            f'scheduler_replication_ops_total{{op="skipped"}} '
            f"{self.replication_skipped}",
            # Shape-class prewarm surface (PR 18, ROADMAP item 3): how
            # many of the registry's classes are traced vs registered —
            # done < registry on a scrape means a half-warm standby
            # whose promotion would still pay compiles.
            "# TYPE scheduler_registry_classes gauge",
            f"scheduler_registry_classes {self.registry_classes}",
            "# TYPE scheduler_prewarmed_classes gauge",
            f"scheduler_prewarmed_classes {self.prewarm_classes_done}",
        ]
        return pb.MetricsResponse(
            prometheus_text=self.metrics.render() + "\n".join(extra) + "\n"
        )

    def Debugz(self, request: pb.DebugzRequest, context) -> pb.DebugzResponse:
        """Last-N stitched traces from the span ring (+ flight-recorder
        dumps on request), as JSON — tools/tracez.py converts to
        Chrome/Perfetto trace-event format. A debug surface: span
        records follow tpusched.trace.span_dict, not a stable API."""
        # <= 0 (absent OR a hostile negative) falls back to the default:
        # traces(last=-1) must not become an unbounded response.
        n = int(request.max_traces)
        if n <= 0:
            n = 16
        traces = {
            tid: [tracing.span_dict(s) for s in spans]
            for tid, spans in self._trace.traces(last=n).items()
        }
        flight = ""
        if request.include_flight:
            flight = json.dumps(self.flight.dumps())
        return pb.DebugzResponse(
            trace_json=json.dumps({"traces": traces}), flight_json=flight
        )

    def Statusz(self, request: pb.StatuszRequest,
                context) -> pb.StatuszResponse:
        """The cycle flight ledger (round 18, ISSUE 13): rolling
        p50/p99 per stage, warm-path mix, churn/round aggregates, the
        compile timeline, sentinel anomaly counts, and the last-N
        CycleRecords — plus this replica's identity facts so
        tools/statusz.py's fleet merge can label columns. Served on
        standbys too (observability must not promote), like Health/
        Metrics/Debugz. A debug surface: record JSON follows
        tpusched.ledger.SCHEMA, not a stable API."""
        n = int(request.max_records)
        n = 32 if n <= 0 else min(n, 256)
        payload = self.ledger.statusz(last=n)
        # Wire panel (round 19, ISSUE 19): the per-cycle round-trip
        # decomposition — component quantiles, byte totals, the clock
        # offset, coverage, and last-N WireRecords (tpusched.wire
        # SCHEMA). Raw bucket counts ride along for the fleet merge.
        payload["wire"] = self.wire.statusz(last=n)
        # Ingest panel (PR 20, ISSUE 20): front-door admission counters
        # plus live queue depth/capacity, when this server has a gate.
        if self.ingest is not None:
            payload["ingest"] = self.ingest.stats()
        lad = self._ladder.snapshot()
        payload["role"] = self.role
        payload["serving_path"] = lad["level"]
        payload["watchdog_trips"] = self.watchdog_trips
        payload["flight_dumps"] = self.flight.trips
        return pb.StatuszResponse(statusz_json=json.dumps(payload))

    def Explainz(self, request: pb.ExplainzRequest,
                 context) -> pb.ExplainzResponse:
        """Decision provenance (round 12): last-N DecisionRecords as
        JSON summaries plus targeted queries — `pod` answers "why is P
        pending / why did P land there" (full per-pod decision with the
        score-term breakdown), `victim` answers "who evicted V" (victim
        terms + evictor's decision + the auction round chain). Like
        Debugz, a debug surface: JSON follows tpusched.explain
        record_dict, not a stable API. Record summaries stay bounded
        (per-pod decisions ship only for the requested pod)."""
        col = self.explain
        n = int(request.max_records)
        n = 8 if n <= 0 else min(n, 64)
        payload: dict = dict(
            enabled=col.enabled,
            recorded=col.recorded,
            records=[
                explaining.record_dict(
                    r, include_auction=bool(request.include_auction))
                for r in col.last(n)
            ],
        )
        if request.pod:
            payload["why"] = col.why(request.pod)
        if request.victim:
            payload["who_evicted"] = col.who_evicted(request.victim)
        return pb.ExplainzResponse(explain_json=json.dumps(payload))

    def Enqueue(self, request: pb.EnqueueRequest,
                context) -> pb.EnqueueResponse:
        """The bounded front door (PR 20, ISSUE 20): offer a batch of
        pending pods to the ingest gate. A partially shed batch is a
        SUCCESS carrying the shed names + retry-after hint (the caller
        re-offers just those); a FULLY shed batch aborts
        RESOURCE_EXHAUSTED, which rpc/client.py's RETRYABLE_CODES
        already backs off and re-drives — the PR 3 retry contract is
        the load-shedding protocol. An injected ``ingest.enqueue``
        error surfaces as UNAVAILABLE (same contract). Admission is
        exactly-once across those retries: the gate dedups by name."""
        if self.ingest is None:
            self._abort(context, grpc.StatusCode.UNIMPLEMENTED,
                        "this server has no ingest gate "
                        "(make_server ingest=...)")
        submitted = float(request.submitted) or time.time()
        pods = [
            dict(name=p.name, priority=float(p.priority),
                 slo_target=float(p.slo_target), submitted=submitted)
            for p in request.pods
        ]
        try:
            res = self.ingest.offer(pods, tenant=int(request.tenant))
        except FaultError as e:
            self._abort(context, grpc.StatusCode.UNAVAILABLE,
                        f"ingest fault: {e}")
        if pods and not res["admitted"]:
            self._abort(
                context, grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"ingest shed all {len(pods)} pods; retry after "
                f"{res['retry_after_s']:.3f}s")
        return pb.EnqueueResponse(
            admitted=len(res["admitted"]), shed=len(res["shed"]),
            shed_pods=res["shed"],
            queue_depth=int(res["queue_depth"]),
            retry_after_s=float(res["retry_after_s"]),
        )


def make_server(
    address: str = "127.0.0.1:0",
    config: EngineConfig | None = None,
    buckets: Buckets | None = None,
    max_workers: int = 8,
    log_stream=None,
    audit_stream=None,
    device_sessions: int = DEVICE_SESSION_CAP,
    faults=None,
    watchdog_s: float = WATCHDOG_S,
    ladder: DegradationLadder | None = None,
    tracer=None,
    flight: FlightRecorder | None = None,
    role: str = "leader",
    replication_log: "ReplicationLog | None" = None,
    explain=False,
    explain_k: int = 3,
    warm: "str | None" = None,
    ledger: "ledgering.CycleLedger | None" = None,
    ledger_jsonl: "str | None" = None,
    prewarm: bool = False,
    wire: "wiring.WireLedger | None" = None,
    wire_profile_dir: "str | None" = None,
    ingest=None,
):
    """Build (grpc.Server, bound_port, service). Unlimited message size:
    a 10k-pod snapshot exceeds the 4 MB default. max_workers default 8:
    4 concurrent clients each keeping 2 requests in flight must all get
    a decode thread — the dispatch gate, not the thread pool, is the
    serialization point. Call svc.close() after server.stop() to drain
    the engine's fetch worker and drop device-resident sessions.
    faults/watchdog_s/ladder: failure-domain knobs; tracer/flight:
    observability knobs; role/replication_log: fleet knobs
    (SchedulerService; tpusched/replicate.py ReplicaSet wires a
    standby's follower loop); explain/explain_k: decision provenance
    (round 12 — True or an ExplainCollector makes every Assign an
    explained cycle, served by the Explainz rpc); warm: warm-solve
    routing for session-backed delta Assigns (round 17, ISSUE 12 —
    None | "bitwise" | "incremental"; SchedulerService docstring);
    ledger/ledger_jsonl: the cycle flight ledger + its optional JSONL
    black box (round 18, ISSUE 13 — served by the Statusz rpc /
    tools/statusz.py); prewarm: boot-time tracing of the full
    shape-class registry (PR 18 — needs explicit buckets; the service's
    prewarm_complete / Health field 12 flips when every class is
    compiled, and ReplicaSet.wait_caught_up blocks on it);
    wire/wire_profile_dir: the wire ledger + its optional anomaly-armed
    device-trace capture directory (round 19, ISSUE 19 — clients
    constructed with wire=svc.wire feed the server's Statusz `wire`
    panel; SchedulerService docstring); ingest: the admission-
    controlled front door served by the Enqueue rpc (PR 20, ISSUE 20 —
    None leaves Enqueue UNIMPLEMENTED; an IngestGate, a dict of
    queue/gate knobs, or True builds one; SchedulerService
    docstring)."""
    svc = SchedulerService(config, buckets, log_stream=log_stream,
                           audit_stream=audit_stream,
                           device_sessions=device_sessions,
                           faults=faults, watchdog_s=watchdog_s,
                           ladder=ladder, tracer=tracer, flight=flight,
                           role=role, replication_log=replication_log,
                           explain=explain, explain_k=explain_k,
                           warm=warm, ledger=ledger,
                           ledger_jsonl=ledger_jsonl, prewarm=prewarm,
                           wire=wire, wire_profile_dir=wire_profile_dir,
                           ingest=ingest)

    def handler(fn, req_cls):
        return grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )

    table = {
        "ScoreBatch": handler(svc.ScoreBatch, pb.ScoreRequest),
        "Assign": handler(svc.Assign, pb.AssignRequest),
        "Health": handler(svc.Health, pb.HealthRequest),
        "Metrics": handler(svc.Metrics, pb.MetricsRequest),
        "Debugz": handler(svc.Debugz, pb.DebugzRequest),
        "Replicate": handler(svc.Replicate, pb.ReplicateRequest),
        "Explainz": handler(svc.Explainz, pb.ExplainzRequest),
        "Statusz": handler(svc.Statusz, pb.StatuszRequest),
        "Enqueue": handler(svc.Enqueue, pb.EnqueueRequest),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", -1),
            ("grpc.max_send_message_length", -1),
        ],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, table),)
    )
    port = server.add_insecure_port(address)
    return server, port, svc


def serve(address: str = "127.0.0.1:50051", config: EngineConfig | None = None,
          audit_path: str | None = None, watchdog_s: float = WATCHDOG_S,
          explain: bool = False, ledger_jsonl: str | None = None,
          buckets: Buckets | None = None, prewarm: bool = False,
          compile_cache: str | None = None):
    """Blocking entry point: python -m tpusched.rpc.server"""
    # Persistent XLA cache first (PR 18): a restarted sidecar then
    # reloads its programs instead of recompiling them — prewarm still
    # traces each class, but the trace hits the on-disk cache.
    shapeclass.enable_persistent_cache(compile_cache)
    audit = open(audit_path, "a") if audit_path else None
    server, port, svc = make_server(address, config, buckets=buckets,
                                    audit_stream=audit,
                                    watchdog_s=watchdog_s, explain=explain,
                                    ledger_jsonl=ledger_jsonl,
                                    prewarm=prewarm)
    server.start()
    print(f"tpusched sidecar listening on port {port}", file=sys.stderr)
    try:
        server.wait_for_termination()
    finally:
        svc.close()


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--address", default="127.0.0.1:50051")
    ap.add_argument("--config", default=None, help="EngineConfig YAML path")
    ap.add_argument("--audit", default=None,
                    help="append per-pod placement audit JSONL to this file")
    ap.add_argument("--watchdog-s", type=float, default=WATCHDOG_S,
                    help="per-dispatch result-join budget before a hung "
                         "solve is aborted as DEADLINE_EXCEEDED")
    ap.add_argument("--explain", action="store_true",
                    help="record decision provenance for every Assign "
                         "(served by the Explainz rpc / tools/explainz.py)")
    ap.add_argument("--ledger-jsonl", default=None,
                    help="append every cycle flight-ledger record to "
                         "this JSONL black box (round 18; the Statusz "
                         "rpc serves the in-memory ring either way)")
    ap.add_argument("--buckets", default=None, metavar="PODSxNODES[xRUN]",
                    help="explicit floor buckets, e.g. 256x64 or "
                         "256x64x512 (Buckets.fit) — pins compile "
                         "shapes; required by --prewarm")
    ap.add_argument("--prewarm", action="store_true",
                    help="trace the full shape-class registry at boot "
                         "(PR 18: zero request-path compiles afterward; "
                         "needs --buckets)")
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent XLA compilation cache directory "
                         "(default: $TPUSCHED_COMPILE_CACHE when set) — "
                         "a restarted sidecar reloads programs instead "
                         "of recompiling")
    args = ap.parse_args()
    cfg = None
    if args.config:
        from tpusched.config import load_config

        cfg = load_config(args.config)
    bk = None
    if args.buckets:
        dims = [int(x) for x in args.buckets.lower().split("x")]
        if len(dims) not in (2, 3):
            ap.error("--buckets wants PODSxNODES or PODSxNODESxRUNNING")
        bk = Buckets.fit(*dims)
    serve(args.address, cfg, audit_path=args.audit,
          watchdog_s=args.watchdog_s, explain=args.explain,
          ledger_jsonl=args.ledger_jsonl, buckets=bk,
          prewarm=args.prewarm, compile_cache=args.compile_cache)
