"""gRPC boundary (SURVEY.md C12): proto codec, sidecar server, client.

Regenerate the pb2 module after editing protos/tpusched.proto:
    protoc -Iprotos --python_out=tpusched/rpc protos/tpusched.proto

The codec half (pb + snapshot_to/from_proto) is pure protobuf and
imports eagerly; the server/client half needs grpc and loads LAZILY
via module __getattr__ (round 15, TPL001 cleanup) so grpc stays an
OPTIONAL dep: `tpusched.host`/`tpusched.kube`/the in-process sim all
reach the codec through this package and must import on a grpc-free
install — exactly the boundary the TPL001 allowlist protects.
"""

from tpusched.rpc import tpusched_pb2 as pb
from tpusched.rpc.codec import snapshot_from_proto, snapshot_to_proto

__all__ = [
    "pb",
    "snapshot_from_proto",
    "snapshot_to_proto",
    "SchedulerService",
    "make_server",
    "SchedulerClient",
]

# name -> owning module for the grpc-backed exports.
_GRPC_EXPORTS = {
    "SchedulerService": "tpusched.rpc.server",
    "make_server": "tpusched.rpc.server",
    "SchedulerClient": "tpusched.rpc.client",
}


def __getattr__(name):
    if name in _GRPC_EXPORTS:
        import importlib  # tpl: disable=TPL001(lazy public API: the grpc-backed half loads on first attribute access only)

        return getattr(importlib.import_module(_GRPC_EXPORTS[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
