"""gRPC boundary (SURVEY.md C12): proto codec, sidecar server, client.

Regenerate the pb2 module after editing protos/tpusched.proto:
    protoc -Iprotos --python_out=tpusched/rpc protos/tpusched.proto
"""

from tpusched.rpc import tpusched_pb2 as pb
from tpusched.rpc.codec import snapshot_from_proto, snapshot_to_proto
from tpusched.rpc.server import SchedulerService, make_server
from tpusched.rpc.client import SchedulerClient

__all__ = [
    "pb",
    "snapshot_from_proto",
    "snapshot_to_proto",
    "SchedulerService",
    "make_server",
    "SchedulerClient",
]
