"""Proto <-> SnapshotBuilder codec (SURVEY.md C12).

The wire model is spec-level records; this module is the single place
where they meet the engine's host-side interning (SnapshotBuilder).
snapshot_to_proto exists for clients/tests that already hold builder
-style records (the host shim uses it); a Go scheduler would emit the
proto directly from its cache.
"""

from __future__ import annotations

import logging
import os
import traceback

from tpusched.config import (Buckets, DEFAULT_OBSERVED_AVAIL,
                             DEFAULT_SLO_TARGET, EngineConfig)
from tpusched.rpc import tpusched_pb2 as pb
from tpusched.snapshot import (
    MatchExpression,
    NodeSelectorTerm,
    PodAffinityTerm,
    PreferredTerm,
    SnapshotBuilder,
    Toleration,
    TopologySpreadConstraint,
)


def decode_snapshot(
    msg: "pb.ClusterSnapshot | bytes",
    config: EngineConfig | None = None,
    buckets: Buckets | None = None,
    prefer_native: bool | None = None,
):
    """Decode a wire snapshot, preferring the native C++ decoder
    (tpusched.native, ~8x faster at 10k x 5k and exactly equal to the
    Python path) when it is available. prefer_native=None consults the
    TPUSCHED_NO_NATIVE env toggle; False forces the Python path.

    `msg` may be the parsed message or its serialized BYTES: the
    sidecar's delta path composes snapshots as concatenated per-record
    wire bytes (SnapshotStore.compose_bytes) and hands them straight to
    the native parser — no Python message is ever materialized there.
    For a parsed message, the re-serialization feeding the native
    parser is upb-backed and costs ~5 ms at 10k x 5k (measured) — noise
    next to the ~350 ms of Python decode it replaces.

    A native decode error falls back to the Python path: if the input
    is genuinely bad, Python raises the authoritative error; if it was
    a native-only limitation (e.g. exotic numeric literals), the slow
    path still serves the request."""
    if prefer_native is None:
        prefer_native = os.environ.get("TPUSCHED_NO_NATIVE", "") in ("", "0")
    if prefer_native:
        from tpusched import native  # tpl: disable=TPL001(the native .so is optional and may BUILD on first import; the pure-python path must not pay or risk that at module import)

        if native.available():
            try:
                data = (msg if isinstance(msg, bytes)
                        else msg.SerializeToString())
                return native.decode_snapshot_bytes(data, config, buckets)
            except Exception:
                # The fallback must be LOUD: a native decode failure is
                # either a contract bug (native.py calls it "a bug in
                # this file") or a permanent ~8x decode slowdown.
                logging.getLogger("tpusched.native").warning(
                    "native decode failed; falling back to the Python "
                    "decoder for this request:\n%s",
                    traceback.format_exc(limit=3),
                )
    if isinstance(msg, bytes):
        msg = pb.ClusterSnapshot.FromString(msg)
    return snapshot_from_proto(msg, config, buckets)


def _res_map(resources) -> dict[str, float]:
    return {r.name: r.quantity for r in resources}


def _labels(labels) -> dict[str, str]:
    return {l.key: l.value for l in labels}


def _exprs(msgs) -> tuple[MatchExpression, ...]:
    return tuple(
        MatchExpression(m.key, m.op, tuple(m.values)) for m in msgs
    )


def _affinity(msgs) -> list[PodAffinityTerm]:
    return [
        PodAffinityTerm(
            topology_key=t.topology_key,
            selector=_exprs(t.selector),
            anti=t.anti,
            required=t.required,
            weight=t.weight or 1.0,
            namespaces=tuple(t.namespaces),
        )
        for t in msgs
    ]


def node_kwargs(n: "pb.Node") -> dict:
    """Wire Node -> SnapshotBuilder.add_node kwargs (incl. 'name').
    The single proto->record authority, shared by the full decoder and
    the device-resident delta path (rpc.server.DeviceSession)."""
    return dict(
        name=n.name,
        allocatable=_res_map(n.allocatable),
        labels=_labels(n.labels),
        taints=[(t.key, t.value, t.effect) for t in n.taints],
        used=_res_map(n.used),
        unschedulable=n.unschedulable,
    )


def pod_kwargs(p: "pb.PendingPod") -> dict:
    """Wire PendingPod -> SnapshotBuilder.add_pod kwargs (incl. 'name')."""
    return dict(
        name=p.name,
        requests=_res_map(p.requests),
        priority=p.priority,
        slo_target=p.slo_target,
        # proto3 cannot distinguish unset from 0.0: clients must set
        # observed_availability explicitly (0.0 means 0.0; a pod with
        # no SLO is unaffected either way since pressure clips at 0).
        observed_avail=p.observed_availability,
        labels=_labels(p.labels),
        node_selector=_labels(p.node_selector),
        required_terms=[
            NodeSelectorTerm(_exprs(t.expressions))
            for t in p.required_terms
        ],
        preferred_terms=[
            PreferredTerm(t.weight, NodeSelectorTerm(_exprs(t.term.expressions)))
            for t in p.preferred_terms
        ],
        tolerations=[
            Toleration(t.key, t.operator or "Equal", t.value, t.effect)
            for t in p.tolerations
        ],
        topology_spread=[
            TopologySpreadConstraint(
                topology_key=c.topology_key,
                max_skew=c.max_skew,
                when_unsatisfiable=c.when_unsatisfiable,
                selector=_exprs(c.selector),
            )
            for c in p.topology_spread
        ],
        pod_affinity=_affinity(p.pod_affinity),
        pod_group=p.pod_group or None,
        pod_group_min_member=p.pod_group_min_member,
        namespace=p.namespace or "default",
    )


def running_kwargs(r: "pb.RunningPod") -> dict:
    """Wire RunningPod -> SnapshotBuilder.add_running_pod kwargs, plus
    'name' (the builder doesn't key running pods; delta paths do)."""
    return dict(
        name=r.name,
        node=r.node,
        requests=_res_map(r.requests),
        priority=r.priority,
        slack=r.slack,
        labels=_labels(r.labels),
        count_into_used=not r.exclude_from_used,
        pod_affinity=_affinity(r.pod_affinity),
        namespace=r.namespace or "default",
        pdb_group=r.pdb_group or None,
        pdb_disruptions_allowed=r.pdb_disruptions_allowed,
    )


def snapshot_from_proto(
    msg: pb.ClusterSnapshot,
    config: EngineConfig | None = None,
    buckets: Buckets | None = None,
):
    """Decode a wire snapshot into a built (ClusterSnapshot, SnapshotMeta).

    Records are processed in NAME order, not wire order: index-based
    tie-breaks (lowest node index among score maxima, submission order
    among equal priorities) are then deterministic for a given cluster
    STATE regardless of how the records were transported — a full send
    and a delta-path recompose of the same state schedule identically."""
    config = config or EngineConfig()
    b = SnapshotBuilder(config, buckets)
    for n in _by_name(msg.nodes):
        b.add_node(**node_kwargs(n))
    for p in _by_name(msg.pods):
        b.add_pod(**pod_kwargs(p))
    for r in _by_name(msg.running):
        kw = running_kwargs(r)
        kw.pop("name")
        b.add_running_pod(**kw)
    snap, meta = b.build()
    # Running-pod names travel with meta for eviction responses — in the
    # same name-sorted order the arrays were built in, so evicted[m]
    # resolves to the right pod whatever the wire order was.
    meta.running_names = [
        r.name or f"running-{i}" for i, r in enumerate(_by_name(msg.running))
    ]
    return snap, meta


# ---------------------------------------------------------------------------
# Delta snapshots (SURVEY.md §7 hard part 6).
# ---------------------------------------------------------------------------


class UnknownBase(KeyError):
    """Delta referenced a base_id the store no longer holds."""


def _by_name(coll):
    """Canonical record order (see snapshot_from_proto): sort by name
    WITHOUT copying messages (decode is the hot path). Running pods may
    be unnamed; Python's stable sort keeps their relative wire order."""
    return sorted(coll, key=lambda r: r.name)


def delta_safe(msg: pb.ClusterSnapshot) -> bool:
    """A snapshot is usable as a delta base only if every record carries
    a unique non-empty name: the stores key by name, so unnamed or
    duplicate records would silently collapse on the delta path."""
    for coll in (msg.nodes, msg.pods, msg.running):
        names = [r.name for r in coll]
        if any(not n for n in names) or len(set(names)) != len(names):
            return False
    return True


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class SnapshotStore:
    """Name-keyed record store of one snapshot's proto sub-messages
    (messages OR their serialized bytes), so a SnapshotDelta can be
    applied and the full ClusterSnapshot recomposed server-side. Wire
    savings: the client ships only changed records; the recompose +
    re-intern cost stays on the sidecar host.

    The sidecar stores BYTES (set_full_bytes): applying a delta then
    serializes only the churned records, and compose_bytes() builds the
    full serialized snapshot by pure concatenation — protobuf wire
    format allows a repeated field's entries to appear anywhere in the
    stream — feeding the native decoder with no Python message at all."""

    def __init__(self, msg: pb.ClusterSnapshot | None = None):
        self.nodes: dict[str, "pb.Node | bytes"] = {}
        self.pods: dict[str, "pb.PendingPod | bytes"] = {}
        self.running: dict[str, "pb.RunningPod | bytes"] = {}
        if msg is not None:
            self.set_full(msg)

    def set_full(self, msg: pb.ClusterSnapshot) -> None:
        self.nodes = {n.name: n for n in msg.nodes}
        self.pods = {p.name: p for p in msg.pods}
        self.running = {r.name: r for r in msg.running}

    def set_full_bytes(self, msg: pb.ClusterSnapshot) -> None:
        """Store serialized records (one upb serialize pass per record,
        full sends only); later delta cycles reuse the bytes."""
        self.nodes = {n.name: n.SerializeToString() for n in msg.nodes}
        self.pods = {p.name: p.SerializeToString() for p in msg.pods}
        self.running = {r.name: r.SerializeToString() for r in msg.running}

    def copy(self) -> "SnapshotStore":
        st = SnapshotStore()
        st.nodes, st.pods, st.running = (
            dict(self.nodes), dict(self.pods), dict(self.running)
        )
        return st

    def nbytes(self) -> int:
        """Retained payload bytes (serialized record sizes; message-
        typed entries report ByteSize) — feeds the sidecar's
        scheduler_device_bytes{kind="byte_stores"} gauge (round 12).
        Copies share record objects, so summing every registered
        store OVERCOUNTS shared bytes; the gauge documents that."""
        total = 0
        for coll in (self.nodes, self.pods, self.running):
            for v in coll.values():
                total += (len(v) if isinstance(v, (bytes, bytearray))
                          else v.ByteSize())
        return total

    def apply_delta(self, delta: pb.SnapshotDelta) -> None:
        """Upserts are stored as bytes when the store holds bytes
        (serialize churn only), as messages otherwise."""
        as_bytes = any(
            isinstance(next(iter(d.values()), None), bytes)
            for d in (self.nodes, self.pods, self.running)
        )

        def put(d, rec):
            d[rec.name] = rec.SerializeToString() if as_bytes else rec

        for n in delta.upsert_nodes:
            put(self.nodes, n)
        for name in delta.remove_nodes:
            self.nodes.pop(name, None)
        for p in delta.upsert_pods:
            put(self.pods, p)
        for name in delta.remove_pods:
            self.pods.pop(name, None)
        for r in delta.upsert_running:
            put(self.running, r)
        for name in delta.remove_running:
            self.running.pop(name, None)

    def compose(self) -> pb.ClusterSnapshot:
        msg = pb.ClusterSnapshot()
        if any(isinstance(v, bytes) for v in
               (*self.nodes.values(), *self.pods.values(),
                *self.running.values())):
            return pb.ClusterSnapshot.FromString(self.compose_bytes())
        msg.nodes.extend(self.nodes.values())
        msg.pods.extend(self.pods.values())
        msg.running.extend(self.running.values())
        return msg

    # ClusterSnapshot field tags, wire type 2 (length-delimited):
    # (1<<3)|2, (2<<3)|2, (3<<3)|2.
    _TAGS = (b"\x0a", b"\x12", b"\x1a")

    def compose_bytes(self) -> bytes:
        """Serialized ClusterSnapshot by concatenating length-delimited
        record fields — a few ms at 10k x 5k vs ~25 ms for message
        compose + re-serialize. Record order is irrelevant: the decoder
        canonicalizes by name (snapshot_from_proto sorts; the native
        decoder matches it)."""
        parts = []
        for tag, d in zip(self._TAGS,
                          (self.nodes, self.pods, self.running)):
            for rec in d.values():
                raw = _ser(rec)
                parts.append(tag)
                parts.append(_varint(len(raw)))
                parts.append(raw)
        return b"".join(parts)


def _ser(rec) -> bytes:
    return rec if isinstance(rec, bytes) else rec.SerializeToString()


def delta_between(prev: SnapshotStore, msg: pb.ClusterSnapshot,
                  base_id: str,
                  new_bytes: SnapshotStore | None = None,
                  changed: "set[str] | None" = None) -> pb.SnapshotDelta:
    """Client-side diff: the SnapshotDelta turning `prev` into `msg`.
    Record equality by serialized bytes. `prev` values may be messages
    or pre-serialized bytes (DeltaSession stores bytes so that a caller
    mutating its snapshot message in place between calls — the records
    would then alias — still diffs against what was actually sent).

    new_bytes: optional empty SnapshotStore; when given, filled with
    msg's per-record serialized bytes so the caller can remember them
    as the next base without serializing everything a second time.

    changed: optional set of record names the caller knows may have
    changed since the base (an informer-driven client knows exactly
    which objects its watch events touched). Base records NOT named are
    trusted byte-identical and skipped without re-serialization, making
    the per-cycle diff O(churn) instead of O(cluster) serialization
    work (~100 ms at 10k x 5k). Additions and removals are still
    detected by name regardless. CONTRACT: a caller that mutates a
    record without naming it here ships a stale record and the sidecar
    solves a stale snapshot — name everything you touch."""
    delta = pb.SnapshotDelta(base_id=base_id)
    if changed is not None and not isinstance(changed, set):
        changed = set(changed)

    def diff(prev_d, coll, upserts, removes, out_d):
        new_names = set()
        for rec in coll:
            new_names.add(rec.name)
            old = prev_d.get(rec.name)
            if (changed is not None and old is not None
                    and rec.name not in changed):
                if out_d is not None:
                    out_d[rec.name] = _ser(old)
                continue
            raw = rec.SerializeToString()
            if out_d is not None:
                out_d[rec.name] = raw
            if old is None or _ser(old) != raw:
                upserts.append(rec)
        removes.extend(k for k in prev_d if k not in new_names)

    nb = new_bytes
    diff(prev.nodes, msg.nodes, delta.upsert_nodes, delta.remove_nodes,
         nb.nodes if nb else None)
    diff(prev.pods, msg.pods, delta.upsert_pods, delta.remove_pods,
         nb.pods if nb else None)
    diff(prev.running, msg.running, delta.upsert_running,
         delta.remove_running, nb.running if nb else None)
    return delta


# ---------------------------------------------------------------------------
# Encoder (host shim / tests).
# ---------------------------------------------------------------------------


def _set_resources(field, mapping):
    for name, q in mapping.items():
        r = field.add()
        r.name, r.quantity = name, float(q)


def _set_labels(field, mapping):
    for k, v in sorted(mapping.items()):
        l = field.add()
        l.key, l.value = k, v


def _set_exprs(field, exprs):
    for e in exprs:
        m = field.add()
        m.key, m.op = e.key, e.op
        m.values.extend(e.values)


def _set_affinity(field, terms):
    for t in terms:
        m = field.add()
        m.topology_key = t.topology_key
        _set_exprs(m.selector, t.selector)
        m.anti, m.required, m.weight = t.anti, t.required, float(t.weight)
        m.namespaces.extend(t.namespaces)


def snapshot_to_proto(
    nodes: list[dict], pods: list[dict], running: list[dict] | None = None
) -> pb.ClusterSnapshot:
    """Encode builder-style records (the kwargs SnapshotBuilder.add_*
    take, plus 'name'/'node') into a wire snapshot."""
    msg = pb.ClusterSnapshot()
    for n in nodes:
        nm = msg.nodes.add()
        nm.name = n["name"]
        _set_resources(nm.allocatable, n.get("allocatable", {}))
        _set_labels(nm.labels, n.get("labels", {}))
        _set_resources(nm.used, n.get("used", {}))
        for (k, v, e) in n.get("taints", []):
            t = nm.taints.add()
            t.key, t.value, t.effect = k, v, e
        if n.get("unschedulable"):
            nm.unschedulable = True
    for p in pods:
        pm = msg.pods.add()
        pm.name = p["name"]
        _set_resources(pm.requests, p.get("requests", {}))
        pm.priority = float(p.get("priority", 0.0))
        pm.slo_target = float(p.get("slo_target", DEFAULT_SLO_TARGET))
        pm.observed_availability = float(
            p.get("observed_avail", DEFAULT_OBSERVED_AVAIL))
        _set_labels(pm.labels, p.get("labels", {}))
        _set_labels(pm.node_selector, p.get("node_selector", {}))
        for term in p.get("required_terms", []):
            tm = pm.required_terms.add()
            _set_exprs(tm.expressions, term.expressions)
        for pt in p.get("preferred_terms", []):
            tm = pm.preferred_terms.add()
            tm.weight = float(pt.weight)
            _set_exprs(tm.term.expressions, pt.term.expressions)
        for tol in p.get("tolerations", []):
            t = pm.tolerations.add()
            t.key, t.operator, t.value, t.effect = (
                tol.key, tol.operator, tol.value, tol.effect
            )
        for c in p.get("topology_spread", []):
            cm = pm.topology_spread.add()
            cm.topology_key = c.topology_key
            cm.max_skew = int(c.max_skew)
            cm.when_unsatisfiable = c.when_unsatisfiable
            _set_exprs(cm.selector, c.selector)
        _set_affinity(pm.pod_affinity, p.get("pod_affinity", []))
        if p.get("pod_group"):
            pm.pod_group = p["pod_group"]
            pm.pod_group_min_member = int(p.get("pod_group_min_member", 0))
        if p.get("namespace"):
            pm.namespace = p["namespace"]
    for r in running or []:
        rm = msg.running.add()
        rm.name = r.get("name", "")
        rm.node = r["node"]
        _set_resources(rm.requests, r.get("requests", {}))
        rm.priority = float(r.get("priority", 0.0))
        rm.slack = float(r.get("slack", 0.0))
        _set_labels(rm.labels, r.get("labels", {}))
        _set_affinity(rm.pod_affinity, r.get("pod_affinity", []))
        rm.exclude_from_used = not r.get("count_into_used", True)
        if r.get("namespace"):
            rm.namespace = r["namespace"]
        if r.get("pdb_group"):
            rm.pdb_group = r["pdb_group"]
            rm.pdb_disruptions_allowed = int(
                r.get("pdb_disruptions_allowed", 0)
            )
    return msg
