"""Proto <-> SnapshotBuilder codec (SURVEY.md C12).

The wire model is spec-level records; this module is the single place
where they meet the engine's host-side interning (SnapshotBuilder).
snapshot_to_proto exists for clients/tests that already hold builder
-style records (the host shim uses it); a Go scheduler would emit the
proto directly from its cache.
"""

from __future__ import annotations

from tpusched.config import Buckets, EngineConfig
from tpusched.rpc import tpusched_pb2 as pb
from tpusched.snapshot import (
    MatchExpression,
    NodeSelectorTerm,
    PodAffinityTerm,
    PreferredTerm,
    SnapshotBuilder,
    Toleration,
    TopologySpreadConstraint,
)


def _res_map(resources) -> dict[str, float]:
    return {r.name: r.quantity for r in resources}


def _labels(labels) -> dict[str, str]:
    return {l.key: l.value for l in labels}


def _exprs(msgs) -> tuple[MatchExpression, ...]:
    return tuple(
        MatchExpression(m.key, m.op, tuple(m.values)) for m in msgs
    )


def _affinity(msgs) -> list[PodAffinityTerm]:
    return [
        PodAffinityTerm(
            topology_key=t.topology_key,
            selector=_exprs(t.selector),
            anti=t.anti,
            required=t.required,
            weight=t.weight or 1.0,
            namespaces=tuple(t.namespaces),
        )
        for t in msgs
    ]


def snapshot_from_proto(
    msg: pb.ClusterSnapshot,
    config: EngineConfig | None = None,
    buckets: Buckets | None = None,
):
    """Decode a wire snapshot into a built (ClusterSnapshot, SnapshotMeta)."""
    config = config or EngineConfig()
    b = SnapshotBuilder(config, buckets)
    for n in msg.nodes:
        b.add_node(
            n.name,
            allocatable=_res_map(n.allocatable),
            labels=_labels(n.labels),
            taints=[(t.key, t.value, t.effect) for t in n.taints],
            used=_res_map(n.used),
        )
    for p in msg.pods:
        b.add_pod(
            p.name,
            requests=_res_map(p.requests),
            priority=p.priority,
            slo_target=p.slo_target,
            # proto3 cannot distinguish unset from 0.0: clients must set
            # observed_availability explicitly (0.0 means 0.0; a pod with
            # no SLO is unaffected either way since pressure clips at 0).
            observed_avail=p.observed_availability,
            labels=_labels(p.labels),
            node_selector=_labels(p.node_selector),
            required_terms=[
                NodeSelectorTerm(_exprs(t.expressions))
                for t in p.required_terms
            ],
            preferred_terms=[
                PreferredTerm(t.weight, NodeSelectorTerm(_exprs(t.term.expressions)))
                for t in p.preferred_terms
            ],
            tolerations=[
                Toleration(t.key, t.operator or "Equal", t.value, t.effect)
                for t in p.tolerations
            ],
            topology_spread=[
                TopologySpreadConstraint(
                    topology_key=c.topology_key,
                    max_skew=c.max_skew,
                    when_unsatisfiable=c.when_unsatisfiable,
                    selector=_exprs(c.selector),
                )
                for c in p.topology_spread
            ],
            pod_affinity=_affinity(p.pod_affinity),
            pod_group=p.pod_group or None,
            pod_group_min_member=p.pod_group_min_member,
            namespace=p.namespace or "default",
        )
    for r in msg.running:
        b.add_running_pod(
            node=r.node,
            requests=_res_map(r.requests),
            priority=r.priority,
            slack=r.slack,
            labels=_labels(r.labels),
            count_into_used=not r.exclude_from_used,
            pod_affinity=_affinity(r.pod_affinity),
            namespace=r.namespace or "default",
        )
    snap, meta = b.build()
    # Running-pod names travel with meta for eviction responses.
    meta.running_names = [r.name or f"running-{i}" for i, r in enumerate(msg.running)]
    return snap, meta


# ---------------------------------------------------------------------------
# Encoder (host shim / tests).
# ---------------------------------------------------------------------------


def _set_resources(field, mapping):
    for name, q in mapping.items():
        r = field.add()
        r.name, r.quantity = name, float(q)


def _set_labels(field, mapping):
    for k, v in sorted(mapping.items()):
        l = field.add()
        l.key, l.value = k, v


def _set_exprs(field, exprs):
    for e in exprs:
        m = field.add()
        m.key, m.op = e.key, e.op
        m.values.extend(e.values)


def _set_affinity(field, terms):
    for t in terms:
        m = field.add()
        m.topology_key = t.topology_key
        _set_exprs(m.selector, t.selector)
        m.anti, m.required, m.weight = t.anti, t.required, float(t.weight)
        m.namespaces.extend(t.namespaces)


def snapshot_to_proto(
    nodes: list[dict], pods: list[dict], running: list[dict] | None = None
) -> pb.ClusterSnapshot:
    """Encode builder-style records (the kwargs SnapshotBuilder.add_*
    take, plus 'name'/'node') into a wire snapshot."""
    msg = pb.ClusterSnapshot()
    for n in nodes:
        nm = msg.nodes.add()
        nm.name = n["name"]
        _set_resources(nm.allocatable, n.get("allocatable", {}))
        _set_labels(nm.labels, n.get("labels", {}))
        _set_resources(nm.used, n.get("used", {}))
        for (k, v, e) in n.get("taints", []):
            t = nm.taints.add()
            t.key, t.value, t.effect = k, v, e
    for p in pods:
        pm = msg.pods.add()
        pm.name = p["name"]
        _set_resources(pm.requests, p.get("requests", {}))
        pm.priority = float(p.get("priority", 0.0))
        pm.slo_target = float(p.get("slo_target", 0.0))
        pm.observed_availability = float(p.get("observed_avail", 1.0))
        _set_labels(pm.labels, p.get("labels", {}))
        _set_labels(pm.node_selector, p.get("node_selector", {}))
        for term in p.get("required_terms", []):
            tm = pm.required_terms.add()
            _set_exprs(tm.expressions, term.expressions)
        for pt in p.get("preferred_terms", []):
            tm = pm.preferred_terms.add()
            tm.weight = float(pt.weight)
            _set_exprs(tm.term.expressions, pt.term.expressions)
        for tol in p.get("tolerations", []):
            t = pm.tolerations.add()
            t.key, t.operator, t.value, t.effect = (
                tol.key, tol.operator, tol.value, tol.effect
            )
        for c in p.get("topology_spread", []):
            cm = pm.topology_spread.add()
            cm.topology_key = c.topology_key
            cm.max_skew = int(c.max_skew)
            cm.when_unsatisfiable = c.when_unsatisfiable
            _set_exprs(cm.selector, c.selector)
        _set_affinity(pm.pod_affinity, p.get("pod_affinity", []))
        if p.get("pod_group"):
            pm.pod_group = p["pod_group"]
            pm.pod_group_min_member = int(p.get("pod_group_min_member", 0))
        if p.get("namespace"):
            pm.namespace = p["namespace"]
    for r in running or []:
        rm = msg.running.add()
        rm.name = r.get("name", "")
        rm.node = r["node"]
        _set_resources(rm.requests, r.get("requests", {}))
        rm.priority = float(r.get("priority", 0.0))
        rm.slack = float(r.get("slack", 0.0))
        _set_labels(rm.labels, r.get("labels", {}))
        _set_affinity(rm.pod_affinity, r.get("pod_affinity", []))
        rm.exclude_from_used = not r.get("count_into_used", True)
        if r.get("namespace"):
            rm.namespace = r["namespace"]
    return msg
