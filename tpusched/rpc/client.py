"""Python client for the tpusched sidecar (SURVEY.md C12).

Mirrors what the Go `--score-backend=tpu` plugin would do: serialize the
cluster snapshot, call ScoreBatch (the Score-plugin path) or Assign (the
full batched solve), read back scores/assignments by name.

Failure-domain contract (round 8, ISSUE 3 — the client half of the
taxonomy documented in rpc/server.py):

  * every RPC carries a DEADLINE (the channel-level timeout);
  * RETRYABLE statuses (UNAVAILABLE — sidecar restarting;
    RESOURCE_EXHAUSTED — dispatch-gate admission refused) retry with
    capped exponential backoff + jitter inside the original deadline
    budget (RetryPolicy);
  * RESYNC statuses (FAILED_PRECONDITION — unknown base / degraded
    stateless mode) make DeltaSession fall back to a full send and the
    pipelines transparently re-send the doomed cycles as full
    snapshots recomposed from the pinned store (no lost responses);
  * everything else is FATAL and surfaces to the caller.

Retry-safety: every delta is stamped with (lineage_id, seq); a retry
whose first attempt was applied-but-unacked is deduped server-side and
the cached response replayed (SnapshotDelta proto comment).

Replica failover (round 11, ISSUE 6): SchedulerClient accepts an
ORDERED endpoint list; UNAVAILABLE rotates to the next replica before
the retry re-sends (both the blocking _call loop and the pipelines'
_join_entry re-issues). A warm standby answers the retried delta from
its replicated stores under the same snapshot_ids; a cold one answers
FAILED_PRECONDITION and the resync machinery above takes over — so
failover composes with, rather than replaces, the ISSUE 3 contract.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
import uuid

import grpc
import numpy as np

from tpusched import trace as tracing
from tpusched import wire as wiring
from tpusched.rpc import codec
from tpusched.rpc import tpusched_pb2 as pb
from tpusched.rpc.server import SERVICE

# Error taxonomy (rpc/server.py module docstring is the authority).
RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
})
RESYNC_CODES = frozenset({grpc.StatusCode.FAILED_PRECONDITION})


def classify_error(code) -> str:
    """'retryable' | 'resync' | 'fatal' for a grpc StatusCode."""
    if code in RETRYABLE_CODES:
        return "retryable"
    if code in RESYNC_CODES:
        return "resync"
    return "fatal"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff + jitter for RETRYABLE statuses.
    Retries always stay inside the caller's original deadline budget —
    the deadline is the contract, the retries are how the budget is
    spent. jitter_frac spreads K clients retrying a restarted sidecar
    so they don't re-arrive in lockstep (the thundering-herd half of
    the kube-scheduler backoff discipline)."""

    max_attempts: int = 6
    initial_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    multiplier: float = 2.0
    jitter_frac: float = 0.25
    codes: frozenset = RETRYABLE_CODES

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Delay before retry `attempt` (0-based)."""
        base = min(
            self.initial_backoff_s * self.multiplier ** attempt,
            self.max_backoff_s,
        )
        return base * (1.0 + self.jitter_frac * (2.0 * rng.random() - 1.0))


# Retries disabled: surface the first error (tests pin exact statuses).
NO_RETRY = RetryPolicy(max_attempts=1)


class _MethodRef:
    """Stable handle for one rpc method that resolves the CURRENT
    channel's stub at call time, so a failover mid-retry-loop (the
    channel and its stubs are rebuilt) transparently redirects every
    holder — retry loops, pipelines re-issuing futures — without them
    re-reading client attributes."""

    __slots__ = ("_client", "_name")

    def __init__(self, client: "SchedulerClient", name: str):
        self._client = client
        self._name = name

    def __call__(self, request, timeout=None):
        return self._client._stubs[self._name](request, timeout=timeout)

    def future(self, request, timeout=None):
        return self._client._stubs[self._name].future(
            request, timeout=timeout)


def score_response_arrays(resp: pb.ScoreResponse):
    """(feasible[P,N] bool, scores[P,N] f32) from either the row or the
    packed-bytes ScoreResponse form."""
    P, N = len(resp.pod_names), len(resp.node_names)
    if resp.scores_packed:
        feas = np.frombuffer(resp.feasible_packed, np.uint8)
        return (
            feas.reshape(P, N).astype(bool),
            # Zero-copy (read-only) view of the message buffer — an
            # astype here would duplicate 200 MB at 10k x 5k.
            np.frombuffer(resp.scores_packed, "<f4").reshape(P, N),
        )
    if resp.k:
        raise ValueError(
            "response carries the top-k form; use score_topk_arrays"
        )
    feas = np.zeros((P, N), bool)
    scores = np.zeros((P, N), np.float32)
    for i, row in enumerate(resp.rows):
        feas[i] = row.feasible
        scores[i] = row.scores
    return feas, scores


def score_topk_arrays(resp: pb.ScoreResponse):
    """(idx[P,k] int32 node indices with -1 padding, scores[P,k] f32)
    from the top-k ScoreResponse form (request.top_k > 0). Indices
    resolve against resp.node_names (the decoder's canonical sorted
    order). A zero-node snapshot yields [P,0] arrays."""
    P = len(resp.pod_names)
    if not resp.k:
        if not resp.node_names and not resp.rows:
            # top_k requested on a drained cluster: nothing to rank.
            return (np.zeros((P, 0), np.int32), np.zeros((P, 0), np.float32))
        raise ValueError("response carries no top-k form (request had "
                         "top_k unset)")
    k = resp.k
    return (
        np.frombuffer(resp.topk_idx_packed, "<i4").reshape(P, k),
        np.frombuffer(resp.topk_score_packed, "<f4").reshape(P, k),
    )


def assign_response_arrays(resp: pb.AssignResponse):
    """(pod_names, node_names, node_idx[P] int32 (-1 unplaced),
    score[P] f32, commit_key[P] int32) from the packed AssignResponse
    form (request.packed_ok). node_idx values index into the returned
    node_names — the decoder's canonical sorted order, NOT the request
    wire order. The repeated-Assignment form carries node names inline;
    use .assignments for it. A zero-pod response decodes to empty
    arrays (valid for either form)."""
    if resp.assignments:
        raise ValueError(
            "response carries the repeated-Assignment form; read "
            ".assignments (request had packed_ok unset)"
        )
    return (
        list(resp.pod_names),
        list(resp.node_names),
        np.frombuffer(resp.node_idx_packed, "<i4"),
        np.frombuffer(resp.score_packed, "<f4"),
        np.frombuffer(resp.commit_key_packed, "<i4"),
    )


class SchedulerClient:
    def __init__(self, address, timeout: float = 120.0,
                 retry: RetryPolicy | None = None,
                 retry_seed: int | None = None,
                 tracer=None, wire=None):
        """address: one endpoint, or an ORDERED list of replica
        endpoints (round 11, ISSUE 6) — the client talks to the first
        and FAILS OVER to the next on UNAVAILABLE (a dead/restarting
        sidecar), wrapping around; the promoted standby serves the
        failed-over client's deltas from its replicated stores.
        RESOURCE_EXHAUSTED deliberately does NOT rotate: an overloaded
        leader is alive, and stampeding its standby would promote it
        into a split brain.

        timeout: per-RPC deadline budget (seconds) — retries spend
        the SAME budget, they don't extend it. retry: RetryPolicy for
        RETRYABLE statuses (None = defaults; pass NO_RETRY to surface
        first errors). retry_seed pins the backoff jitter for
        deterministic tests/chaos runs.

        wire: the WireLedger every completed Score/Assign cycle is
        ledgered into (round 19, ISSUE 19) — pass the SIDECAR's own
        ledger (svc.wire) when client and server share a process, so
        the cycles land in the server's Statusz wire panel; None falls
        back to the process-default tpusched.wire.DEFAULT."""
        self.timeout = timeout
        self.retry = retry if retry is not None else RetryPolicy()
        self.retries = 0          # observability: attempts beyond the first
        self.failovers = 0        # endpoint rotations (UNAVAILABLE)
        self._retry_rng = random.Random(retry_seed)
        # Trace stitching (round 9, ISSUE 4): every Score/Assign request
        # is stamped with a trace id (request_id) + the caller's active
        # span (parent_span); the sidecar roots its stage spans there,
        # so the client and server rings merge into one causal trace.
        self.tracer = tracer if tracer is not None else tracing.DEFAULT
        # Wire ledger (round 19, ISSUE 19): every completed Score/
        # Assign cycle is assembled from the shared span ring into one
        # WireRecord. Best-effort — assembly must never fail a call.
        self._wire = wire if wire is not None else wiring.DEFAULT
        self.wire_errors = 0
        self.addresses = ([address] if isinstance(address, str)
                          else list(address))
        if not self.addresses:
            raise ValueError("SchedulerClient needs at least one address")
        self._endpoint_idx = 0
        self._channel = None
        self._stubs: dict = {}
        self._parked: list = []   # pre-failover channels, closed in close()
        # Endpoint GENERATION: bumped on every failover. Callers capture
        # it at issue time and pass it to _maybe_failover so a failure
        # observed on an already-abandoned channel (a pipeline sibling
        # future issued pre-rotation) cannot rotate the client BACK onto
        # the dead endpoint it just left.
        self._gen = 0
        self._failover_lock = threading.Lock()
        self._connect()
        self._score = _MethodRef(self, "ScoreBatch")
        self._assign = _MethodRef(self, "Assign")
        self._health = _MethodRef(self, "Health")
        self._metrics = _MethodRef(self, "Metrics")
        self._debugz = _MethodRef(self, "Debugz")
        self._replicate = _MethodRef(self, "Replicate")
        self._explainz = _MethodRef(self, "Explainz")
        self._statusz = _MethodRef(self, "Statusz")
        self._enqueue = _MethodRef(self, "Enqueue")

    _RPCS = (
        ("ScoreBatch", pb.ScoreRequest, pb.ScoreResponse),
        ("Assign", pb.AssignRequest, pb.AssignResponse),
        ("Health", pb.HealthRequest, pb.HealthResponse),
        ("Metrics", pb.MetricsRequest, pb.MetricsResponse),
        ("Debugz", pb.DebugzRequest, pb.DebugzResponse),
        ("Replicate", pb.ReplicateRequest, pb.ReplicateResponse),
        ("Explainz", pb.ExplainzRequest, pb.ExplainzResponse),
        ("Statusz", pb.StatuszRequest, pb.StatuszResponse),
        ("Enqueue", pb.EnqueueRequest, pb.EnqueueResponse),
    )

    def _connect(self) -> None:
        """(Re)build the channel + raw stubs against the current
        endpoint; the _MethodRef handles callers hold resolve through
        self._stubs, so they all pick up the new channel."""
        self._channel = grpc.insecure_channel(
            self.addresses[self._endpoint_idx],
            options=[
                ("grpc.max_receive_message_length", -1),
                ("grpc.max_send_message_length", -1),
            ],
        )
        self._stubs = {
            name: self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )
            for name, req_cls, resp_cls in self._RPCS
        }

    def endpoint(self) -> str:
        """The endpoint this client currently targets."""
        return self.addresses[self._endpoint_idx]

    def failover(self) -> str:
        """Rotate to the next endpoint in the ordered list (wrapping)
        and rebuild the channel; returns the new endpoint. The old
        channel is NOT closed here — closing would CANCEL a pipeline's
        other in-flight futures (fatal), where letting them fail
        against the dead server yields UNAVAILABLE (retryable, and the
        retry re-issues on the new channel). Parked channels are closed
        by close()."""
        self._parked.append(self._channel)
        # Bound the park lot: a long-lived client on a flapping fleet
        # must not accumulate channels forever. Only the last few
        # generations can still carry live in-flight futures (pipeline
        # joins are FIFO and re-issue promptly on the current channel);
        # closing the oldest beyond that is safe.
        while len(self._parked) > 8:
            self._parked.pop(0).close()
        self._endpoint_idx = (self._endpoint_idx + 1) % len(self.addresses)
        self._gen += 1
        self._connect()
        self.failovers += 1
        self.tracer.record("client.failover", cat="client",
                           to=self.endpoint())
        return self.endpoint()

    def _maybe_failover(self, code, gen: int | None = None) -> bool:
        """Failover trigger (round 11): UNAVAILABLE means the endpoint
        is dead or restarting — with more than one endpoint configured,
        rotate before the retry re-sends. Other retryable statuses stay
        put (see __init__).

        gen: the endpoint generation captured when the failed call was
        ISSUED. If another failure already rotated us off that endpoint
        (gen is stale), stay put — rotating again would point the
        client back at the dead replica and burn retry attempts
        ping-ponging between the corpse and the live standby."""
        if code != grpc.StatusCode.UNAVAILABLE or len(self.addresses) < 2:
            return False
        with self._failover_lock:
            if gen is not None and gen != self._gen:
                return False
            self.failover()
        return True

    def _stamp(self, request, request_id: str = "") -> str:
        """Stamp a Score/Assign request with its trace identity; keeps
        an id the caller (a pipeline re-issue) already minted. With no
        explicit id, an enclosing client span on this thread donates
        its trace: a resync full-send issued under a client.resync span
        parents into the doomed request's trace instead of starting an
        unrelated one."""
        if request_id:
            request.request_id = request_id
        if not request.request_id:
            ctx = self.tracer.current()
            if ctx is not None and ctx[0]:
                request.request_id = ctx[0]
                if not request.parent_span:
                    request.parent_span = ctx[1]
            else:
                request.request_id = self.tracer.new_trace_id()
        elif not request.parent_span:
            ctx = self.tracer.current()
            if ctx is not None and ctx[0] == request.request_id:
                request.parent_span = ctx[1]
        return request.request_id

    def _call(self, method, request, rpc: str = ""):
        """Blocking unary call under the deadline + retry contract:
        RETRYABLE statuses back off (capped, jittered) and re-send
        inside the ORIGINAL deadline budget; a retried delta carries
        its original (lineage_id, seq) so an applied-but-unacked first
        attempt is deduped server-side. Everything else raises.
        _BasePipeline._join_entry is this loop's future-shaped twin —
        keep their retry discipline in lockstep."""
        rid = ""
        ledger = None
        bytes_up = 0
        if "request_id" in type(request).DESCRIPTOR.fields_by_name:
            rid = self._stamp(request)
            if rpc and self._wire.enabled:
                ledger = self._wire
        if ledger is not None:
            # The wire ledger's serialize component: one timed pass
            # over the request (gRPC's own serializer hits protobuf's
            # warmed path right after). Only paid while ledgering —
            # the OFF arm of bench.py's wire overhead check skips it.
            t_ser = time.perf_counter()
            bytes_up = len(request.SerializeToString())
            self.tracer.record(
                "client.serialize", dur_s=time.perf_counter() - t_ser,
                cat="client", ctx=(rid, int(request.parent_span)),
                rpc=rpc, bytes=bytes_up,
            )
        deadline = time.monotonic() + self.timeout
        attempt = 0
        while True:
            remaining = deadline - time.monotonic()
            gen = self._gen
            try:
                if not rid:
                    return method(request, timeout=max(remaining, 1e-3))
                with self.tracer.span("client.send", cat="client",
                                      trace_id=rid,
                                      parent_id=int(request.parent_span),
                                      rpc=rpc, attempt=attempt):
                    resp = method(request, timeout=max(remaining, 1e-3))
                if ledger is not None:
                    self._wire_observe(ledger, rpc, rid, bytes_up,
                                       resp.ByteSize())
                return resp
            except grpc.RpcError as e:
                attempt += 1
                if (e.code() not in self.retry.codes
                        or attempt >= self.retry.max_attempts):
                    raise
                delay = self.retry.backoff_s(attempt - 1, self._retry_rng)
                if deadline - time.monotonic() <= delay:
                    raise
                self.retries += 1
                # Replica failover (round 11): a dead endpoint rotates
                # BEFORE the backoff, so the retry re-sends against the
                # next replica in the ordered list.
                self._maybe_failover(e.code(), gen)
                time.sleep(delay)
                if rid:
                    # The backoff wait, as a span: retries are visible
                    # gaps in the stitched trace, not silent latency.
                    self.tracer.record(
                        "client.retry", dur_s=delay, cat="client",
                        ctx=(rid, int(request.parent_span)),
                        rpc=rpc, code=e.code().name, attempt=attempt,
                    )

    def _wire_observe(self, ledger, rpc: str, rid: str, bytes_up: int,
                      bytes_down: int, source: str = "call") -> None:
        """Assemble + ledger one completed cycle from the shared span
        ring (tpusched.wire.assemble). Best-effort by contract: a
        ledger bug must never fail a call that already succeeded —
        failures count in self.wire_errors instead of raising."""
        try:
            rec = wiring.assemble(
                rid, rpc, self.tracer.spans(rid), ledger.clock,
                bytes_up=bytes_up, bytes_down=bytes_down, source=source,
            )
            if rec is not None:
                ledger.observe(rec)
        except Exception:
            self.wire_errors += 1

    def health(self) -> pb.HealthResponse:
        return self._call(self._health, pb.HealthRequest())

    def replicate(self, from_seq: int,
                  follower_id: str = "") -> pb.ReplicateResponse:
        """Fetch replication ops from the current endpoint (round 11;
        StandbyFollower's poll — see tpusched/replicate.py)."""
        return self._call(
            self._replicate,
            pb.ReplicateRequest(from_seq=int(from_seq),
                                follower_id=follower_id),
        )

    def score_batch(self, snapshot: pb.ClusterSnapshot, *,
                    packed_ok: bool = False,
                    top_k: int = 0) -> pb.ScoreResponse:
        return self._call(
            self._score,
            pb.ScoreRequest(snapshot=snapshot, packed_ok=packed_ok,
                            top_k=top_k),
            rpc="ScoreBatch",
        )

    def assign(self, snapshot: pb.ClusterSnapshot, *,
               packed_ok: bool = False) -> pb.AssignResponse:
        return self._call(
            self._assign,
            pb.AssignRequest(snapshot=snapshot, packed_ok=packed_ok),
            rpc="Assign",
        )

    def _send_future(self, method, request, rpc: str, request_id: str):
        """Issue a stamped future; the send itself is an instant span
        (the in-flight wait is the caller's join — pipelines record it
        as client.join against the same trace id)."""
        rid = self._stamp(request, request_id)
        self.tracer.record("client.send", cat="client",
                           ctx=(rid, int(request.parent_span)), rpc=rpc)
        return method.future(request, timeout=self.timeout)

    def assign_future(self, snapshot: pb.ClusterSnapshot, *,
                      packed_ok: bool = False, request_id: str = ""):
        """Non-blocking Assign: returns a grpc Future. With the
        sidecar's staged handlers (decode outside the dispatch lane), a
        second in-flight request is what lets ONE client overlap its
        next request's decode with the previous solve — see
        AssignPipeline."""
        return self._send_future(
            self._assign,
            pb.AssignRequest(snapshot=snapshot, packed_ok=packed_ok),
            "Assign", request_id,
        )

    def assign_delta_future(self, delta: pb.SnapshotDelta, *,
                            packed_ok: bool = False, request_id: str = ""):
        return self._send_future(
            self._assign,
            pb.AssignRequest(delta=delta, packed_ok=packed_ok),
            "Assign", request_id,
        )

    def score_batch_delta(self, delta: pb.SnapshotDelta, *,
                          packed_ok: bool = False,
                          top_k: int = 0) -> pb.ScoreResponse:
        return self._call(
            self._score,
            pb.ScoreRequest(delta=delta, packed_ok=packed_ok, top_k=top_k),
            rpc="ScoreBatch",
        )

    def score_batch_future(self, snapshot: pb.ClusterSnapshot, *,
                           packed_ok: bool = False, top_k: int = 0,
                           request_id: str = ""):
        """Non-blocking ScoreBatch (see assign_future): the second
        in-flight request that lets ONE scoring client overlap its next
        request's decode with the previous ranking — ScorePipeline."""
        return self._send_future(
            self._score,
            pb.ScoreRequest(snapshot=snapshot, packed_ok=packed_ok,
                            top_k=top_k),
            "ScoreBatch", request_id,
        )

    def score_batch_delta_future(self, delta: pb.SnapshotDelta, *,
                                 packed_ok: bool = False, top_k: int = 0,
                                 request_id: str = ""):
        return self._send_future(
            self._score,
            pb.ScoreRequest(delta=delta, packed_ok=packed_ok, top_k=top_k),
            "ScoreBatch", request_id,
        )

    def assign_delta(self, delta: pb.SnapshotDelta, *,
                     packed_ok: bool = False) -> pb.AssignResponse:
        return self._call(
            self._assign,
            pb.AssignRequest(delta=delta, packed_ok=packed_ok),
            rpc="Assign",
        )

    def metrics_text(self) -> str:
        return self._call(self._metrics, pb.MetricsRequest()).prometheus_text

    def debugz(self, max_traces: int = 16,
               include_flight: bool = False) -> pb.DebugzResponse:
        """Fetch the sidecar's last-N traces (+ flight dumps) — see
        SchedulerService.Debugz and tools/tracez.py."""
        return self._call(
            self._debugz,
            pb.DebugzRequest(max_traces=max_traces,
                             include_flight=include_flight),
        )

    def explainz(self, pod: str = "", victim: str = "",
                 max_records: int = 8,
                 include_auction: bool = False) -> pb.ExplainzResponse:
        """Decision provenance (round 12): last-N DecisionRecord
        summaries plus "why is `pod` pending/placed" and "who evicted
        `victim`" — see SchedulerService.Explainz and
        tools/explainz.py."""
        return self._call(
            self._explainz,
            pb.ExplainzRequest(pod=pod, victim=victim,
                               max_records=max_records,
                               include_auction=include_auction),
        )

    def statusz(self, max_records: int = 32) -> pb.StatuszResponse:
        """Cycle flight ledger (round 18, ISSUE 13): rolling per-stage
        p50/p99, warm-path mix, compile timeline, sentinel anomalies,
        and the last-N CycleRecords as one JSON payload — see
        SchedulerService.Statusz and tools/statusz.py."""
        return self._call(
            self._statusz,
            pb.StatuszRequest(max_records=int(max_records)),
        )

    def enqueue(self, pods, tenant: int = 0,
                submitted: float = 0.0) -> pb.EnqueueResponse:
        """Offer a batch through the admission-controlled front door
        (PR 20, ISSUE 20). `pods` is a list of pb.PendingPod messages
        or builder-style dicts (name / priority / slo_target). A
        FULLY shed batch is RESOURCE_EXHAUSTED — already in
        RETRYABLE_CODES, so this call backs off and re-offers inside
        its deadline budget without new machinery; the server dedups
        admitted names so the retry is exactly-once. A partial shed
        returns OK with resp.shed_pods for the caller to re-offer."""
        req = pb.EnqueueRequest(tenant=int(tenant),
                                submitted=float(submitted))
        for p in pods:
            if isinstance(p, pb.PendingPod):
                req.pods.add().CopyFrom(p)
            else:
                req.pods.add(name=p["name"],
                             priority=float(p.get("priority", 0.0)),
                             slo_target=float(p.get("slo_target", 0.0)))
        return self._call(self._enqueue, req, rpc="Enqueue")

    def close(self):
        self._channel.close()
        for ch in self._parked:
            ch.close()
        self._parked = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DeltaSession:
    """Transparent delta transport over a SchedulerClient (SURVEY.md §7
    hard part 6): callers always pass the FULL wire snapshot; the
    session diffs it against what the sidecar last acknowledged and
    ships only changed records. Falls back to a full send when the
    sidecar no longer holds the base (FAILED_PRECONDITION — e.g. after
    a sidecar restart or LRU eviction), which also makes crash recovery
    automatic: state lives only as an optimization."""

    def __init__(self, client: SchedulerClient):
        self.client = client
        self._base: codec.SnapshotStore | None = None
        self._base_id: str | None = None
        # Retry-safety lineage identity: every delta this session sends
        # carries (lineage_id, seq) so a client-level retry of an
        # applied-but-unacked delta replays server-side (proto comment).
        self._lineage_id = uuid.uuid4().hex[:16]
        self._seq = 0
        # After a fallback (sidecar restart / base evicted from its LRU),
        # skip the delta attempt for exponentially more sends: a client
        # whose base is always evicted (many interleaved sessions) must
        # not pay a failed delta RPC + full resend on every cycle.
        self._skip_delta = 0
        self._consec_fallbacks = 0
        # Wire accounting for benchmarks/observability.
        self.full_sends = 0
        self.delta_sends = 0
        self.fallbacks = 0
        self.bytes_sent = 0
        self.bytes_full_equiv = 0

    def _call(self, snapshot: pb.ClusterSnapshot, send_full, send_delta,
              changed: "set[str] | None" = None):
        full_bytes = snapshot.ByteSize()
        self.bytes_full_equiv += full_bytes
        if (
            self._base is not None
            and self._base_id is not None
            and self._skip_delta == 0
            # The NEW snapshot must itself be delta-safe: the server's
            # name-keyed store would silently collapse unnamed/duplicate
            # records arriving as delta upserts and solve a corrupted
            # snapshot for this cycle. (_remember only drops the base
            # for the NEXT cycle — one cycle too late.)
            and codec.delta_safe(snapshot)
        ):
            new_bytes = codec.SnapshotStore()
            delta = codec.delta_between(
                self._base, snapshot, self._base_id, new_bytes=new_bytes,
                changed=changed,
            )
            self._seq += 1
            delta.lineage_id = self._lineage_id
            delta.seq = self._seq
            self.bytes_sent += delta.ByteSize()  # transmitted even on reject
            try:
                resp = send_delta(delta)
                self.delta_sends += 1
                self._consec_fallbacks = 0
                self._remember(snapshot, resp.snapshot_id, new_bytes)
                return resp
            except grpc.RpcError as e:
                if e.code() != grpc.StatusCode.FAILED_PRECONDITION:
                    raise
                self.fallbacks += 1
                self._consec_fallbacks += 1
                if self._consec_fallbacks >= 2:
                    self._skip_delta = min(
                        2 ** (self._consec_fallbacks - 1), 64
                    )
                self._base = self._base_id = None
                # Minted trace id: the span AND the full send under it
                # (which inherits the id via _stamp) group as one trace
                # in Debugz — trace_id=None here would record untraced.
                with self.client.tracer.span(
                    "client.resync", cat="client",
                    trace_id=self.client.tracer.new_trace_id(),
                    lineage=self._lineage_id, seq=self._seq,
                ):
                    resp = send_full(snapshot)
                self.full_sends += 1
                self.bytes_sent += full_bytes
                # delta_safe already verified this cycle (guard above).
                self._remember(snapshot, resp.snapshot_id, verified=True)
                return resp
        elif self._skip_delta > 0:
            self._skip_delta -= 1
        resp = send_full(snapshot)
        self.full_sends += 1
        self.bytes_sent += full_bytes
        self._remember(snapshot, resp.snapshot_id)
        return resp

    def _remember(self, snapshot: pb.ClusterSnapshot, sid: str,
                  prebuilt: "codec.SnapshotStore | None" = None,
                  verified: bool = False) -> None:
        """Record what was sent, as per-record BYTES: immune to the
        caller mutating its message in place afterwards, and usable only
        when the snapshot is delta-safe (unique non-empty names — the
        stores key by name). `prebuilt` reuses the bytes delta_between
        already serialized for the diff (no second serialization pass)."""
        # prebuilt/verified only arrive from paths that already checked
        # delta_safe this cycle — don't re-scan all records.
        if not sid or (prebuilt is None and not verified
                       and not codec.delta_safe(snapshot)):
            self._base = self._base_id = None
            return
        if prebuilt is not None:
            st = prebuilt
        else:
            st = codec.SnapshotStore()
            st.set_full_bytes(snapshot)
        self._base = st
        self._base_id = sid

    def assign(self, snapshot: pb.ClusterSnapshot,
               changed: "set[str] | None" = None,
               **kw) -> pb.AssignResponse:
        """changed: optional names of records the caller knows it
        touched since the last call (watch-event driven); makes the
        diff O(churn) — see codec.delta_between."""
        return self._call(
            snapshot,
            lambda s: self.client.assign(s, **kw),
            lambda d: self.client.assign_delta(d, **kw),
            changed=changed,
        )

    def score_batch(self, snapshot: pb.ClusterSnapshot,
                    changed: "set[str] | None" = None,
                    **kw) -> pb.ScoreResponse:
        return self._call(
            snapshot,
            lambda s: self.client.score_batch(s, **kw),
            lambda d: self.client.score_batch_delta(d, **kw),
            changed=changed,
        )


class StaleBase(Exception):
    """An in-flight pipelined delta named a base the sidecar no longer
    holds (restart / LRU eviction) and transparent resync is OFF
    (auto_resync=False). The caller still has its current snapshot:
    re-pin by submitting it with changed=None (a full send).
    `completed` carries the responses that HAD already been received
    before the stale request — earlier cycles' assignments are handed
    to the caller, not dropped in the unwind.

    With auto_resync (the default) this never escapes: the pipeline
    recomposes each doomed cycle's FULL snapshot from its pinned store
    plus that cycle's cumulative delta and re-sends it, so every
    submitted cycle still yields exactly one response — the crash-
    resync path with the end-state-identical guarantee (ISSUE 3)."""

    def __init__(self, msg: str, completed=()):
        super().__init__(msg)
        self.completed: list = list(completed)


class _BasePipeline:
    """Single-connection pipelined requests (SURVEY.md §2.3 PP at the
    serving boundary): keep up to `depth` requests in flight on ONE
    channel so the sidecar's staged handlers overlap request k+1's
    decode with request k's device work — the single-scheduler
    deployment gets the overlap the two-session wire bench measured,
    without a second scheduler. Subclasses bind the rpc pair
    (_send_full / _send_delta_future): AssignPipeline for solves,
    ScorePipeline for top-k ScoreBatch.

    Delta discipline: DeltaSession advances its base every response,
    but a pipelined delta k+1 cannot diff against snapshot k — k's
    snapshot_id is unknown until its response arrives. Instead the base
    is PINNED: every in-flight delta names the same pinned base and
    carries the CUMULATIVE churn since the pin (the server's LRU
    refreshes the pinned store on every hit, keeping it alive). The pin
    refreshes with a full send (draining the pipe first — the response
    carries the new id) when cumulative churn passes refresh_frac of
    the record count, bounding delta growth at O(cumulative churn).

    For streams of independent or slowly-churning snapshots (replay,
    bench, many-cluster fan-in, a scheduler pipelining speculative
    cycles). One cluster's strictly serial feedback cycles cannot be
    pipelined — same limit as pipeline.solve_stream documents."""

    # Wire-ledger rpc label (subclasses bind the real method pair).
    _rpc = ""

    def __init__(self, client: SchedulerClient, depth: int = 2,
                 refresh_frac: float = 0.25, auto_resync: bool = True):
        self.client = client
        self.depth = max(1, int(depth))
        self.refresh_frac = refresh_frac
        self.auto_resync = auto_resync
        self._pinned: codec.SnapshotStore | None = None
        self._pinned_id: str | None = None
        self._churn: set = set()
        # In-flight entries: dict(fut, delta, packed_ok) — the delta is
        # retained so a retry re-sends the SAME (lineage_id, seq) and a
        # resync can recompose the cycle's full snapshot from pin+delta.
        self._inflight: list = []
        self._lineage_id = uuid.uuid4().hex[:16]
        self._seq = 0
        self.full_sends = 0
        self.delta_sends = 0
        self.bytes_sent = 0
        self.resyncs = 0      # doomed cycles re-sent as full snapshots
        self.retried = 0      # retryable-status future re-issues

    # -- rpc binding (subclass responsibility) ------------------------------

    def _send_full(self, snapshot: pb.ClusterSnapshot, packed_ok: bool):
        raise NotImplementedError

    def _send_delta_future(self, delta: pb.SnapshotDelta, packed_ok: bool,
                           request_id: str = ""):
        raise NotImplementedError

    def _join_entry(self, entry) -> object:
        """Join one in-flight delta under the taxonomy: RETRYABLE
        statuses re-issue the SAME delta future (same lineage/seq —
        the server dedupes an applied-but-unacked first attempt) with
        capped backoff; FAILED_PRECONDITION resyncs the cycle as a
        full send (auto_resync) or raises StaleBase; the rest raise.

        Like SchedulerClient._call, re-issues spend ONE deadline
        budget (client.timeout, measured from this join): without the
        cutoff, each fresh future carries its own full timeout and a
        blackholed sidecar could stall a join for max_attempts x
        timeout instead of roughly the configured budget (the last
        in-flight future can still run to its own deadline — ~2x
        worst case, not Nx).

        This loop is _call's FUTURE-shaped twin, kept separate because
        the first "attempt" here is joining an already-issued future
        and the resync path has no blocking-call analogue — but the
        retry DISCIPLINE (policy codes, attempt cap, backoff-must-fit-
        the-remaining-budget) must stay in lockstep with _call; change
        them together."""
        policy = self.client.retry
        tracer = self.client.tracer
        rid = entry.get("rid", "")
        deadline = time.monotonic() + self.client.timeout
        attempt = 0
        while True:
            try:
                with tracer.span("client.join", cat="client",
                                 trace_id=rid, attempt=attempt):
                    resp = entry["fut"].result()
                if rid and self.client._wire.enabled:
                    self.client._wire_observe(
                        self.client._wire, self._rpc, rid,
                        entry["delta"].ByteSize(), resp.ByteSize(),
                        source="pipeline",
                    )
                return resp
            except grpc.RpcError as e:
                code = e.code()
                if code in policy.codes and attempt < policy.max_attempts - 1:
                    delay = policy.backoff_s(attempt, self.client._retry_rng)
                    if deadline - time.monotonic() > delay:
                        # Same failover trigger as _call: rotate off a
                        # dead endpoint, then re-issue the SAME delta
                        # (same lineage/seq) against the new replica —
                        # its replicated stores hold the pinned base.
                        # The entry's issue-time generation keeps a
                        # SIBLING future's failure (issued pre-rotation
                        # on the dead channel) from rotating us back.
                        self.client._maybe_failover(code, entry.get("gen"))
                        time.sleep(delay)
                        attempt += 1
                        self.retried += 1
                        # Re-issue keeps the SAME trace id as well as
                        # the same (lineage, seq): the retry lands in
                        # the original request's stitched trace.
                        tracer.record("client.retry", dur_s=delay,
                                      cat="client", ctx=(rid, 0),
                                      code=code.name, attempt=attempt)
                        entry["fut"] = self._send_delta_future(
                            entry["delta"], entry["packed_ok"], rid
                        )
                        entry["gen"] = self.client._gen
                        continue
                if code in RESYNC_CODES:
                    return self._resync_entry(entry, e)
                raise

    def _resync_entry(self, entry, err):
        """The sidecar lost this cycle's base (restart, LRU eviction,
        stateless degrade). The cycle is NOT lost: its cumulative delta
        applied to the pinned store reproduces the cycle's exact full
        snapshot — recompose and re-send it as a full request. The pin
        id is cleared (the next submit re-pins with a full send) but
        the pin STORE is kept so remaining in-flight cycles can resync
        the same way."""
        if not self.auto_resync or self._pinned is None:
            self._pinned = self._pinned_id = None
            self._drop_inflight()
            raise StaleBase(str(err)) from err
        with self.client.tracer.span("client.resync", cat="client",
                                     trace_id=entry.get("rid", "")):
            full = self._pinned.copy()
            full.apply_delta(entry["delta"])
            msg = full.compose()
            resp = self._send_full(msg, entry["packed_ok"])
        self.resyncs += 1
        self.full_sends += 1
        self.bytes_sent += msg.ByteSize()
        self._pinned_id = None
        return resp

    def _drop_inflight(self):
        for entry in self._inflight:
            entry["fut"].cancel()
        self._inflight = []

    def submit(self, snapshot: pb.ClusterSnapshot,
               changed: "set[str] | None" = None,
               packed_ok: bool = True) -> list:
        """Enqueue one cycle; returns the responses this call completed
        (drained oldest-first; possibly empty while the pipe fills).
        changed: names mutated since the LAST submit, or None to force
        a full send (also the re-pin path). The delta is serialized
        BEFORE returning, so the caller may mutate `snapshot` in place
        between submits."""
        n_rec = (len(snapshot.nodes) + len(snapshot.pods)
                 + len(snapshot.running))
        churn_next = (
            self._churn | set(changed) if changed is not None else None
        )
        if (
            self._pinned is None or self._pinned_id is None
            or churn_next is None
            or len(churn_next) > self.refresh_frac * max(n_rec, 1)
            or not codec.delta_safe(snapshot)
        ):
            done = self.flush()
            resp = self._send_full(snapshot, packed_ok)
            self.full_sends += 1
            self.bytes_sent += snapshot.ByteSize()
            if resp.snapshot_id and codec.delta_safe(snapshot):
                st = codec.SnapshotStore()
                st.set_full_bytes(snapshot)
                self._pinned, self._pinned_id = st, resp.snapshot_id
                self._churn = set()
            else:
                self._pinned = self._pinned_id = None
            done.append(resp)
            return done
        self._churn = churn_next
        delta = codec.delta_between(
            self._pinned, snapshot, self._pinned_id, changed=self._churn
        )
        self._seq += 1
        delta.lineage_id = self._lineage_id
        delta.seq = self._seq
        self.bytes_sent += delta.ByteSize()
        rid = self.client.tracer.new_trace_id()
        self._inflight.append(dict(
            fut=self._send_delta_future(delta, packed_ok, rid),
            delta=delta, packed_ok=packed_ok, rid=rid,
            gen=self.client._gen,
        ))
        self.delta_sends += 1
        done = []
        while len(self._inflight) >= self.depth:
            self._join_into(done)
        return done

    def flush(self) -> list:
        """Drain every in-flight request, oldest first."""
        out: list = []
        while self._inflight:
            self._join_into(out)
        return out

    def _join_into(self, done: list) -> None:
        """Join the oldest in-flight request into `done`; on StaleBase
        (auto_resync off) the already-joined responses ride the
        exception (`completed`) instead of being lost in the unwind."""
        try:
            done.append(self._join_entry(self._inflight.pop(0)))
        except StaleBase as e:
            e.completed = list(done) + e.completed
            raise


class AssignPipeline(_BasePipeline):
    """Pipelined Assign cycles (see _BasePipeline)."""

    _rpc = "Assign"

    def _send_full(self, snapshot, packed_ok):
        return self.client.assign(snapshot, packed_ok=packed_ok)

    def _send_delta_future(self, delta, packed_ok, request_id=""):
        return self.client.assign_delta_future(
            delta, packed_ok=packed_ok, request_id=request_id
        )


class ScorePipeline(_BasePipeline):
    """Pipelined top-k ScoreBatch cycles: the same depth-`depth`
    pinned-base discipline for the Score-plugin surface, closing the
    round-5 verdict's remaining single-stream gap (parity top-8
    ScoreBatch): with two requests in flight on one connection, cycle
    k+1's decode/delta-apply overlaps cycle k's on-device ranking, so
    the per-cycle wall approaches max(decode, rank + fetch) instead of
    their sum. Coalescer interplay: identical deltas submitted by MANY
    such clients fuse server-side into one dispatch."""

    _rpc = "ScoreBatch"

    def __init__(self, client: SchedulerClient, depth: int = 2,
                 refresh_frac: float = 0.25, top_k: int = 8,
                 auto_resync: bool = True):
        super().__init__(client, depth=depth, refresh_frac=refresh_frac,
                         auto_resync=auto_resync)
        self.top_k = int(top_k)

    def _send_full(self, snapshot, packed_ok):
        return self.client.score_batch(snapshot, packed_ok=packed_ok,
                                       top_k=self.top_k)

    def _send_delta_future(self, delta, packed_ok, request_id=""):
        return self.client.score_batch_delta_future(
            delta, packed_ok=packed_ok, top_k=self.top_k,
            request_id=request_id,
        )
