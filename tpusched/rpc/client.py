"""Python client for the tpusched sidecar (SURVEY.md C12).

Mirrors what the Go `--score-backend=tpu` plugin would do: serialize the
cluster snapshot, call ScoreBatch (the Score-plugin path) or Assign (the
full batched solve), read back scores/assignments by name.
"""

from __future__ import annotations

import grpc

from tpusched.rpc import tpusched_pb2 as pb
from tpusched.rpc.server import SERVICE


class SchedulerClient:
    def __init__(self, address: str, timeout: float = 120.0):
        self.timeout = timeout
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", -1),
                ("grpc.max_send_message_length", -1),
            ],
        )

        def method(name, req_cls, resp_cls):
            return self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )

        self._score = method("ScoreBatch", pb.ScoreRequest, pb.ScoreResponse)
        self._assign = method("Assign", pb.AssignRequest, pb.AssignResponse)
        self._health = method("Health", pb.HealthRequest, pb.HealthResponse)
        self._metrics = method("Metrics", pb.MetricsRequest, pb.MetricsResponse)

    def health(self) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=self.timeout)

    def score_batch(self, snapshot: pb.ClusterSnapshot) -> pb.ScoreResponse:
        return self._score(
            pb.ScoreRequest(snapshot=snapshot), timeout=self.timeout
        )

    def assign(self, snapshot: pb.ClusterSnapshot) -> pb.AssignResponse:
        return self._assign(
            pb.AssignRequest(snapshot=snapshot), timeout=self.timeout
        )

    def metrics_text(self) -> str:
        return self._metrics(
            pb.MetricsRequest(), timeout=self.timeout
        ).prometheus_text

    def close(self):
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
