"""Python client for the tpusched sidecar (SURVEY.md C12).

Mirrors what the Go `--score-backend=tpu` plugin would do: serialize the
cluster snapshot, call ScoreBatch (the Score-plugin path) or Assign (the
full batched solve), read back scores/assignments by name.
"""

from __future__ import annotations

import grpc

from tpusched.rpc import codec
from tpusched.rpc import tpusched_pb2 as pb
from tpusched.rpc.server import SERVICE


class SchedulerClient:
    def __init__(self, address: str, timeout: float = 120.0):
        self.timeout = timeout
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", -1),
                ("grpc.max_send_message_length", -1),
            ],
        )

        def method(name, req_cls, resp_cls):
            return self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )

        self._score = method("ScoreBatch", pb.ScoreRequest, pb.ScoreResponse)
        self._assign = method("Assign", pb.AssignRequest, pb.AssignResponse)
        self._health = method("Health", pb.HealthRequest, pb.HealthResponse)
        self._metrics = method("Metrics", pb.MetricsRequest, pb.MetricsResponse)

    def health(self) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=self.timeout)

    def score_batch(self, snapshot: pb.ClusterSnapshot) -> pb.ScoreResponse:
        return self._score(
            pb.ScoreRequest(snapshot=snapshot), timeout=self.timeout
        )

    def assign(self, snapshot: pb.ClusterSnapshot) -> pb.AssignResponse:
        return self._assign(
            pb.AssignRequest(snapshot=snapshot), timeout=self.timeout
        )

    def score_batch_delta(self, delta: pb.SnapshotDelta) -> pb.ScoreResponse:
        return self._score(pb.ScoreRequest(delta=delta), timeout=self.timeout)

    def assign_delta(self, delta: pb.SnapshotDelta) -> pb.AssignResponse:
        return self._assign(pb.AssignRequest(delta=delta), timeout=self.timeout)

    def metrics_text(self) -> str:
        return self._metrics(
            pb.MetricsRequest(), timeout=self.timeout
        ).prometheus_text

    def close(self):
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DeltaSession:
    """Transparent delta transport over a SchedulerClient (SURVEY.md §7
    hard part 6): callers always pass the FULL wire snapshot; the
    session diffs it against what the sidecar last acknowledged and
    ships only changed records. Falls back to a full send when the
    sidecar no longer holds the base (FAILED_PRECONDITION — e.g. after
    a sidecar restart or LRU eviction), which also makes crash recovery
    automatic: state lives only as an optimization."""

    def __init__(self, client: SchedulerClient):
        self.client = client
        self._base: codec.SnapshotStore | None = None
        self._base_id: str | None = None
        # After a fallback (sidecar restart / base evicted from its LRU),
        # skip the delta attempt for exponentially more sends: a client
        # whose base is always evicted (many interleaved sessions) must
        # not pay a failed delta RPC + full resend on every cycle.
        self._skip_delta = 0
        self._consec_fallbacks = 0
        # Wire accounting for benchmarks/observability.
        self.full_sends = 0
        self.delta_sends = 0
        self.fallbacks = 0
        self.bytes_sent = 0
        self.bytes_full_equiv = 0

    def _call(self, snapshot: pb.ClusterSnapshot, send_full, send_delta):
        full_bytes = snapshot.ByteSize()
        self.bytes_full_equiv += full_bytes
        if (
            self._base is not None
            and self._base_id is not None
            and self._skip_delta == 0
            # The NEW snapshot must itself be delta-safe: the server's
            # name-keyed store would silently collapse unnamed/duplicate
            # records arriving as delta upserts and solve a corrupted
            # snapshot for this cycle. (_remember only drops the base
            # for the NEXT cycle — one cycle too late.)
            and codec.delta_safe(snapshot)
        ):
            new_bytes = codec.SnapshotStore()
            delta = codec.delta_between(
                self._base, snapshot, self._base_id, new_bytes=new_bytes
            )
            self.bytes_sent += delta.ByteSize()  # transmitted even on reject
            try:
                resp = send_delta(delta)
                self.delta_sends += 1
                self._consec_fallbacks = 0
                self._remember(snapshot, resp.snapshot_id, new_bytes)
                return resp
            except grpc.RpcError as e:
                if e.code() != grpc.StatusCode.FAILED_PRECONDITION:
                    raise
                self.fallbacks += 1
                self._consec_fallbacks += 1
                if self._consec_fallbacks >= 2:
                    self._skip_delta = min(
                        2 ** (self._consec_fallbacks - 1), 64
                    )
                self._base = self._base_id = None
                resp = send_full(snapshot)
                self.full_sends += 1
                self.bytes_sent += full_bytes
                # delta_safe already verified this cycle (guard above).
                self._remember(snapshot, resp.snapshot_id, verified=True)
                return resp
        elif self._skip_delta > 0:
            self._skip_delta -= 1
        resp = send_full(snapshot)
        self.full_sends += 1
        self.bytes_sent += full_bytes
        self._remember(snapshot, resp.snapshot_id)
        return resp

    def _remember(self, snapshot: pb.ClusterSnapshot, sid: str,
                  prebuilt: "codec.SnapshotStore | None" = None,
                  verified: bool = False) -> None:
        """Record what was sent, as per-record BYTES: immune to the
        caller mutating its message in place afterwards, and usable only
        when the snapshot is delta-safe (unique non-empty names — the
        stores key by name). `prebuilt` reuses the bytes delta_between
        already serialized for the diff (no second serialization pass)."""
        # prebuilt/verified only arrive from paths that already checked
        # delta_safe this cycle — don't re-scan all records.
        if not sid or (prebuilt is None and not verified
                       and not codec.delta_safe(snapshot)):
            self._base = self._base_id = None
            return
        if prebuilt is not None:
            st = prebuilt
        else:
            st = codec.SnapshotStore()
            st.nodes = {n.name: n.SerializeToString() for n in snapshot.nodes}
            st.pods = {p.name: p.SerializeToString() for p in snapshot.pods}
            st.running = {
                r.name: r.SerializeToString() for r in snapshot.running
            }
        self._base = st
        self._base_id = sid

    def assign(self, snapshot: pb.ClusterSnapshot) -> pb.AssignResponse:
        return self._call(
            snapshot, self.client.assign, self.client.assign_delta
        )

    def score_batch(self, snapshot: pb.ClusterSnapshot) -> pb.ScoreResponse:
        return self._call(
            snapshot, self.client.score_batch, self.client.score_batch_delta
        )
