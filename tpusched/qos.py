"""Dynamic QoS priority (SURVEY.md C10).

The reference project's defining feature (its name is
"k8s-qos-driven-scheduler", /root/reference/README.md:1): pod priority is
not the static pod.spec.priority but a *dynamic* function of how far the
pod is from its availability SLO. Pods below their SLO ("under pressure")
pop from the queue first and may preempt pods with positive slack.

Formulas shared by the oracle and the device kernels:
    pressure(pod)  = clip(slo_target - observed_availability, 0, 1)
    priority(pod)  = base_priority + qos_gain * pressure
    slack(victim)  = observed_availability - slo_target   (>0 = cheap victim)

Pressure also optionally reweights score plugins per pod
(QoSConfig.urgency_reweight): a pod far below its SLO cares about being
placed *now* (pure LeastRequested = emptiest node) rather than about
long-term balance, so its effective plugin weights interpolate toward an
urgent profile holding all weight on least_requested.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from tpusched.config import DEFAULT_OBSERVED_AVAIL, EngineConfig, clamp01

# Ages below this are "never observed": avoids 0/0 at the submission
# instant and gives a pod its fallback-1.0 grace until time has
# actually passed.
MIN_OBSERVED_AGE_S = 1e-9


def pressure_of(slo_target: Any, observed_avail: Any) -> Any:
    """Works on numpy and jax arrays alike (pure ufunc arithmetic);
    `Any` is deliberate — the scalar/np/jnp polymorphism has no common
    stub type on this image."""
    return (slo_target - observed_avail).clip(0.0, 1.0)


def observed_availability(
    submitted: float,
    run_seconds: float,
    bound_at: "float | None",
    now: float,
    default: float = DEFAULT_OBSERVED_AVAIL,
) -> float:
    """Availability of one pod at `now`: banked run time plus the
    current in-progress run (bound_at is the start of the CURRENT bind,
    None while pending), over total age — the running-time-over-
    wall-time ratio the reference scores SLOs against. Never-observed
    pods (age below MIN_OBSERVED_AGE_S) return `default`. The input
    side of the QoS feedback loop: this value feeds pressure_of, which
    feeds effective_priority. Shared by host.FakeApiServer (read-time
    accounting) and sim.lifecycle (cross-requeue history)."""
    age = now - submitted
    if age < MIN_OBSERVED_AGE_S:
        return float(default)
    run = float(run_seconds)
    if bound_at is not None:
        run += max(now - bound_at, 0.0)
    return clamp01(run / age, default=default)


def effective_priority(cfg: EngineConfig, base_priority: Any,
                       slo_target: Any, observed_avail: Any) -> Any:
    return base_priority + cfg.qos.qos_gain * pressure_of(slo_target, observed_avail)


def priority_terms(cfg: EngineConfig, base_priority: Any, slo_target: Any,
                   observed_avail: Any) -> dict[str, Any]:
    """Decompose the dynamic priority into its provenance terms (round
    12, decision provenance): base + qos_boost == effective_priority
    exactly (same formula, same op order). Works on scalars and arrays;
    kernels/explain.py's probe packs the pressure/effective pair from
    this decomposition, and tpusched.explain.pod_decision re-derives
    base/qos_boost per pod (via the record's qos_gain) so "why did P
    pop first" is answerable from the record alone."""
    p = pressure_of(slo_target, observed_avail)
    return {
        "base": base_priority,
        "pressure": p,
        "qos_boost": cfg.qos.qos_gain * p,
        "effective": base_priority + cfg.qos.qos_gain * p,
    }


def slack_of(slo_target: Any, observed_avail: Any) -> Any:
    return observed_avail - slo_target


def victim_effective_priority(cfg: EngineConfig, priority: Any,
                              slack: Any) -> Any:
    """Running pods store slack directly; a victim below its SLO
    (negative slack) gets the same qos_gain boost a pending pod would:
    pressure = clip(-slack, 0, 1)."""
    pressure = (-slack).clip(0.0, 1.0)
    return priority + cfg.qos.qos_gain * pressure


def evict_cost_raw(cfg: EngineConfig, priority: Any, slack: Any) -> Any:
    """Eviction cost before the per-snapshot positive shift (see
    QoSConfig.evict_slack_weight): effective priority, discounted by how
    far ABOVE its SLO the victim runs (cheap victims have QoS to spare).
    Works on numpy and jax arrays (pure ufunc arithmetic)."""
    return (
        victim_effective_priority(cfg, priority, slack)
        - cfg.qos.evict_slack_weight * slack.clip(0.0, 1.0)
    )


_PLUGINS = (
    "least_requested",
    "balanced_allocation",
    "node_affinity",
    "taint_toleration",
    "topology_spread",
    "interpod_affinity",
)


def base_weights(cfg: EngineConfig) -> dict[str, float]:
    return {p: float(getattr(cfg.weights, p)) for p in _PLUGINS}


def effective_weights(cfg: EngineConfig, pressure: Any) -> dict[str, Any]:
    """Per-pod plugin weights. With urgency_reweight, interpolate between
    the configured profile and an all-least-requested urgent profile by
    QoS pressure. `pressure` may be a scalar or a [P] array; weights
    broadcast accordingly."""
    w = base_weights(cfg)
    if not cfg.qos.urgency_reweight:
        return {k: v + 0.0 * pressure if _is_array(pressure) else v
                for k, v in w.items()}
    total = sum(w.values())
    urgent = {p: (total if p == "least_requested" else 0.0) for p in _PLUGINS}
    return {
        p: (1.0 - pressure) * w[p] + pressure * urgent[p] for p in _PLUGINS
    }


def _is_array(x: Any) -> bool:
    return hasattr(x, "shape") and getattr(x, "shape", ()) != ()


def tie_hash(seed: int, pod_index: Any) -> Any:
    """Deterministic per-pod 32-bit mix for the "seeded" tie-break.
    Pure uint32 arithmetic so host ints (oracle) and jax uint32 (device)
    agree bit-for-bit; xxhash-style avalanche constants."""
    if isinstance(pod_index, (int, np.integer)):
        x = (seed * 2654435761 + int(pod_index) * 2246822519) & 0xFFFFFFFF
        x ^= x >> 16
        x = (x * 2246822519) & 0xFFFFFFFF
        x ^= x >> 13
        return x
    import jax.numpy as jnp  # tpl: disable=TPL001(scalar host path stays jax-free; jnp is reached only with device arrays already in hand)

    x = jnp.uint32(seed & 0xFFFFFFFF) * jnp.uint32(2654435761) + (
        pod_index.astype(jnp.uint32) * jnp.uint32(2246822519)
    )
    x = x ^ (x >> 16)
    x = x * jnp.uint32(2246822519)
    x = x ^ (x >> 13)
    return x
