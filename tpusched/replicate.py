"""N-way sidecar replication with warm-standby failover (ISSUE 6).

The (lineage_id, seq) retry machinery of ISSUE 3 made every sidecar
state mutation replayable; this module treats those mutations as an OP
LOG and streams it to standby replicas so the scheduler stops being a
single process:

  * `ReplicationLog` — the leader records one op per store
    registration: "full" (a full-send snapshot, payload = serialized
    ClusterSnapshot) or "delta" (payload = serialized SnapshotDelta
    against a prior op's snapshot_id). Ops carry the SAME snapshot_ids
    the leader handed its clients, so a replica that applied the log
    can answer a failed-over client's delta against a leader-era
    base_id directly — no full-resync storm on takeover.
  * The `Replicate` rpc (rpc/server.py) serves ops from a follower's
    next wanted seq; a follower that fell behind the log's retention
    gets `resync=true` plus ONE full-rebase op (the leader's newest
    store), and resumes from the log end.
  * `StandbyFollower` — the polling loop a standby runs: fetch ops,
    apply them into its own SchedulerService (byte stores + a warm
    DeviceSession for delta lineages), mirror them into its OWN log
    (preserving leader seqs) so a second standby can re-follow a
    promoted leader, and export replication lag. The loop exits on
    takeover (role flip) or stop().
  * `ReplicaSet` — an in-process fleet (tests, chaos harness, sim):
    replica 0 starts as leader, the rest as standbys following the
    ordered endpoint list. `kill_leader()` is the canonical fault;
    clients built on the same address list fail over on UNAVAILABLE
    (rpc/client.py) and the first serving request promotes the standby
    (SchedulerService._maybe_takeover).

Failure domains (the ISSUE 3 taxonomy extends, it does not change):
replication is ASYNC — a client ack never waits on a standby, so the
op(s) in flight at the moment the leader dies may be lost. That is
safe by construction: a failed-over client whose base_id the standby
never saw gets FAILED_PRECONDITION and the existing resync machinery
(DeltaSession fallback / pipeline pinned-base recompose) re-sends the
full snapshot. Warm standby is an optimization with a correctness
floor, exactly like every other cache in the serving path.

Leadership is PROMOTION-BY-FIRST-REQUEST, not an election: any serving
request landing on a standby promotes it, and nothing demotes an old
leader at runtime — a resurrected ex-leader rejoining as a standby can
be re-promoted if a client's retry lands on it while still rotating.
That is a deliberate trade: the ordered endpoint list plus the
generation-guarded failover (rpc/client.py _maybe_failover) keeps
clients parked on the first live replica in practice, and even a
double-promotion only costs a full resync (each "leader" serves
correct answers from whatever state clients re-send) — never a lost or
duplicated bind, because binds are committed by the HOST against the
api server, not by sidecar state. A real multi-writer deployment wants
an external lease (the k8s Lease pattern); the "replica.takeover"
fault site is where that guard would veto.

Fault sites (tpusched.faults): "replica.stream" fires at the top of
every follower poll (error = a failed poll, retried next tick; delay =
replication lag building); "replica.takeover" fires inside a standby's
promotion (error = the takeover is refused with UNAVAILABLE — the
split-brain-attempt guard scenario: the client moves on to the next
endpoint and retries this one later).
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from collections import deque

from tpusched.faults import FaultError
from tpusched.rpc import tpusched_pb2 as pb

# Ops retained before a slow follower is forced onto the full-rebase
# path. Each delta op is O(churn) bytes and each full op O(cluster);
# 256 covers minutes of steady-state serving while bounding memory.
REPLOG_CAP = 256

# Hard byte ceiling on retained payloads (on TOP of the op cap): a
# big-cluster leader in a full-send-heavy mode (ladder-degraded or
# resync-storm traffic, multi-MB snapshots) must not hold 256 x O(MB)
# for followers that may not even exist. Evicting early just moves a
# lagging follower onto the full-rebase path — the protocol's normal
# slow-follower answer, not an error.
REPLOG_MAX_BYTES = 64 << 20

# Follower poll cadence. Replication lag in TIME is ~one poll interval
# plus apply cost; the chaos/bench fleets override it downward so a
# kill-the-leader arrives at a caught-up standby.
POLL_S = 0.2


class ReplicationLog:
    """Bounded, thread-safe op log. The leader appends (minting seqs);
    a standby mirrors leader ops verbatim (preserving seqs) so that
    after a takeover its own appends continue the same sequence and a
    surviving second standby can keep following without a rebase."""

    def __init__(self, cap: int = REPLOG_CAP,
                 max_bytes: int = REPLOG_MAX_BYTES):
        self._lock = threading.Lock()
        self._ops: deque = deque(maxlen=int(cap))
        self._max_bytes = int(max_bytes)
        self._bytes = 0        # retained payload bytes
        self._seq = 0          # newest seq ever seen (minted or mirrored)
        self.appended = 0      # leader-side appends
        self.mirrored = 0      # follower-side mirrors

    @property
    def end_seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def first_seq(self) -> int:
        """Oldest retained seq (0 = empty log)."""
        with self._lock:
            return int(self._ops[0].seq) if self._ops else 0

    def _push_locked(self, op: pb.ReplicationOp) -> None:
        if len(self._ops) == self._ops.maxlen:
            self._bytes -= len(self._ops[0].payload)  # deque will drop it
        self._ops.append(op)
        self._bytes += len(op.payload)
        # Byte ceiling: retain at least the newest op (a caught-up
        # follower needs it; one op over budget beats an empty log).
        while self._bytes > self._max_bytes and len(self._ops) > 1:
            self._bytes -= len(self._ops.popleft().payload)

    def append(self, kind: str, snapshot_id: str, payload: bytes,
               base_id: str = "") -> int:
        with self._lock:
            self._seq += 1
            op = pb.ReplicationOp(
                seq=self._seq, kind=kind, snapshot_id=snapshot_id,
                base_id=base_id, payload=payload,
            )
            self._push_locked(op)
            self.appended += 1
            return self._seq

    def mirror(self, op: pb.ReplicationOp) -> None:
        """Record a leader op on a standby, preserving its seq."""
        with self._lock:
            self._seq = max(self._seq, int(op.seq))
            self._push_locked(op)
            self.mirrored += 1

    def since(self, from_seq: int, max_ops: int = 64):
        """(ops, end_seq, stale): retained ops with seq >= from_seq,
        oldest first, capped at max_ops. stale=True means from_seq
        predates retention — the caller must serve a full rebase."""
        from_seq = max(int(from_seq), 1)
        with self._lock:
            end = self._seq
            if from_seq > end + 1:
                # The caller is AHEAD of this log: it followed a
                # timeline (the old leader's tail) this replica never
                # saw, so after a LAGGING standby's promotion the seq
                # spaces fork. Undetected, the follower would report
                # lag 0 forever while frozen on dead state; forcing the
                # rebase path drops the fork and adopts this leader's
                # newest store, resuming from end_seq + 1.
                return [], end, True
            if not self._ops:
                # Nothing retained. A follower asking for history the
                # log once held (from_seq <= end) is stale; asking for
                # the future is simply caught up.
                return [], end, from_seq <= end
            if from_seq < int(self._ops[0].seq):
                return [], end, True
            out = [op for op in self._ops if int(op.seq) >= from_seq]
            return out[:max_ops], end, False


class StandbyFollower:
    """The standby's replication loop: poll the leader's Replicate rpc
    and apply ops into `svc` (a SchedulerService constructed with
    role="standby"). Owns its client; the thread exits when stopped or
    when the service is promoted out of "standby" (takeover)."""

    def __init__(self, svc, addresses, poll_s: float = POLL_S,
                 follower_id: str = "", timeout: float = 10.0):
        from tpusched.rpc.client import RetryPolicy, SchedulerClient  # tpl: disable=TPL001(cycle: rpc.server imports this module at top, and client imports server back)

        self.svc = svc
        self.poll_s = float(poll_s)
        self.follower_id = follower_id or f"standby-{id(svc):x}"
        self.applied_seq = 0     # newest op seq applied locally
        self.known_end = 0       # leader end_seq at the last good poll
        self.polls = 0
        self.failed_polls = 0
        self.rebase_count = 0
        self._consec_failures = 0
        self._stop = threading.Event()
        # NO_RETRY + explicit failover below: a dead leader must not
        # burn a backoff ladder inside every poll tick — the loop IS
        # the retry, and rotating endpoints finds a promoted leader.
        self._client = SchedulerClient(
            addresses, timeout=timeout, retry=RetryPolicy(max_attempts=1)
        )
        self._thread = threading.Thread(
            target=self._run, name=f"tpusched-replica-{self.follower_id}",
            daemon=True,
        )

    def start(self) -> "StandbyFollower":
        self._thread.start()
        return self

    def lag(self) -> int:
        return max(0, self.known_end - self.applied_seq)

    @property
    def prewarmed(self) -> bool:
        """True once this standby's shape-class prewarm finished (or
        was never configured): the compiled-program half of "warm
        standby", next to the replicated-state half `lag()` measures.
        The standby prewarms the same registry its leader derived —
        ReplicaSet hands every replica identical make_kw (config,
        buckets, prewarm), restarts included — so promotion serves its
        first request compile-free (PR 18)."""
        return bool(getattr(self.svc, "prewarm_complete", True))

    def _run(self) -> None:
        import grpc

        while not self._stop.is_set() and self.svc.role == "standby":
            try:
                self.svc._faults.fire("replica.stream")
                with self.svc._trace.span(
                    "replica.stream", cat="replica",
                    follower=self.follower_id, from_seq=self.applied_seq + 1,
                ) as sp:
                    resp = self._client.replicate(
                        self.applied_seq + 1, follower_id=self.follower_id
                    )
                    self.polls += 1
                    if resp.resync and resp.ops:
                        # Fell behind retention: rebase onto the
                        # leader's newest store, resume from log end.
                        self.svc.replica_rebase(resp.ops[0])
                        self.applied_seq = int(resp.end_seq)
                        self.rebase_count += 1
                    else:
                        for op in resp.ops:
                            if self.svc.role != "standby":
                                # Promoted mid-batch (a client request
                                # won the role lock): the remaining
                                # old-leader ops are refused anyway —
                                # stop applying, the loop exits next
                                # time around.
                                break
                            try:
                                self.svc.replica_apply(op)
                            except Exception:
                                # A deterministically-bad op (unknown
                                # kind, corrupt payload) must not wedge
                                # the stream: skip PAST it — same
                                # correctness floor as a missing base,
                                # the failed-over client heals through
                                # FAILED_PRECONDITION + full resync.
                                self.svc.replication_skipped += 1
                                logging.getLogger(
                                    "tpusched.replicate"
                                ).warning(
                                    "skipping unappliable replication "
                                    "op seq=%s kind=%s:\n%s", op.seq,
                                    op.kind,
                                    traceback.format_exc(limit=2),
                                )
                            self.applied_seq = int(op.seq)
                    self.known_end = max(int(resp.end_seq),
                                         self.applied_seq)
                    sp.attrs.update(ops=len(resp.ops),
                                    lag=self.lag(), resync=resp.resync)
                self.svc.replication_lag = self.lag()
                self._consec_failures = 0
                if resp.role != "leader" and len(self._client.addresses) > 1:
                    # A peer STANDBY answered (we rotated onto it during
                    # a leader blip). Its mirrored log is valid — the
                    # ops above were applied — but following a follower
                    # adds a lag hop and its end_seq underreports ours,
                    # so keep rotating until a leader answers.
                    self._client.failover()
            except grpc.RpcError as e:
                self.failed_polls += 1
                self._consec_failures += 1
                # A DEAD or restarting leader answers UNAVAILABLE:
                # rotate immediately (a promoted standby answers at the
                # next endpoint). A HUNG one answers DEADLINE_EXCEEDED
                # (or a crashed handler UNKNOWN) — rotate after a few
                # consecutive failures of any kind, so a wedged peer
                # cannot hold the replication stream hostage.
                if len(self._client.addresses) > 1 and (
                        e.code() == grpc.StatusCode.UNAVAILABLE
                        or self._consec_failures >= 3):
                    self._client.failover()
                    self._consec_failures = 0
            except FaultError:
                # An injected replica.stream shot — the scenario's
                # deterministic failed poll: count it quietly (plans
                # fire these every tick) and keep the loop alive;
                # replication lag is the observable consequence.
                self.failed_polls += 1
            except Exception:
                # A real bug in the poll/apply path must not degrade
                # into silent, permanent lag: count AND log it.
                self.failed_polls += 1
                logging.getLogger("tpusched.replicate").warning(
                    "replication poll failed (follower %s):\n%s",
                    self.follower_id, traceback.format_exc(limit=3),
                )
            self._stop.wait(self.poll_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
        self._client.close()


class ReplicaSet:
    """An in-process fleet of N sidecar replicas on one host: replica 0
    leads, replicas 1..N-1 run StandbyFollowers against the ordered
    endpoint list. The chaos harness, the replicate tests, and the sim
    driver's replicated gRPC backend all build on this; production
    deployments run the same roles as separate processes."""

    def __init__(self, n: int = 2, poll_s: float = POLL_S,
                 follower_timeout: float = 10.0, **make_kw):
        from tpusched.rpc.server import make_server  # tpl: disable=TPL001(cycle: rpc.server imports this module at top, and client imports server back)

        if n < 1:
            raise ValueError(f"replica count must be >= 1, got {n}")
        self._make_kw = dict(make_kw)
        self._poll_s = poll_s
        self._follower_timeout = follower_timeout
        self.servers: list = []
        self.ports: list[int] = []
        self.services: list = []
        self.followers: list = [None] * n
        for i in range(n):
            server, port, svc = make_server(
                "127.0.0.1:0", role="leader" if i == 0 else "standby",
                **make_kw,
            )
            server.start()
            self.servers.append(server)
            self.ports.append(port)
            self.services.append(svc)
        for i in range(1, n):
            self.followers[i] = StandbyFollower(
                self.services[i], self._peer_addresses(i),
                poll_s=poll_s, follower_id=f"replica-{i}",
                timeout=follower_timeout,
            ).start()
        self._dead: set[int] = set()

    def _peer_addresses(self, i: int) -> list[str]:
        """Every replica's address except i's own, leader-most first."""
        return [f"127.0.0.1:{p}" for j, p in enumerate(self.ports)
                if j != i]

    def addresses(self) -> list[str]:
        """Client-facing ordered endpoint list (replica 0 first)."""
        return [f"127.0.0.1:{p}" for p in self.ports]

    def leader_index(self) -> int:
        """The live replica currently reporting role=leader (first
        match in replica order; -1 if none — mid-failover window)."""
        for i, svc in enumerate(self.services):
            if i not in self._dead and svc.role == "leader":
                return i
        return -1

    def wait_caught_up(self, timeout: float = 10.0) -> bool:
        """Block until every live standby's applied seq reaches the
        current leader's log end AND every live replica's shape-class
        prewarm — the leader's own boot prewarm included — is complete
        (True), or timeout (False). make_kw's `prewarm=` reaches every
        replica, restarts included, so a standby's registry mirrors its
        leader's. Chaos runs call this before a kill so 'warm standby'
        — replicated state AND compiled programs — is a property the
        harness controls, not a race it hopes to win: after True, the
        leader serves without compiling and a promotion serves its
        first Assign with zero new compiles (PR 18)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            li = self.leader_index()
            if li < 0:
                return False
            end = self.services[li]._replog.end_seq
            lagging = [
                f for i, f in enumerate(self.followers)
                if f is not None and i not in self._dead
                and self.services[i].role == "standby"
                and (f.applied_seq < end
                     or not self.services[i].prewarm_complete)
            ]
            if not lagging and self.services[li].prewarm_complete:
                return True
            time.sleep(min(self._poll_s / 2, 0.05))
        return False

    def kill(self, i: int) -> None:
        """Stop replica i's server + service (its follower too). The
        port is remembered so restart() can resurrect it in place."""
        if i in self._dead:
            return
        self._dead.add(i)
        if self.followers[i] is not None:
            self.followers[i].stop()
            self.followers[i] = None
        self.servers[i].stop(0)
        self.services[i].close()

    def kill_leader(self) -> int:
        """The canonical fault: kill the current leader; returns its
        index (-1 when no live leader exists)."""
        li = self.leader_index()
        if li >= 0:
            self.kill(li)
        return li

    def restart(self, i: int, role: str = "standby") -> None:
        """Resurrect a killed replica on its original port — as a
        STANDBY by default: a crashed ex-leader rejoins the fleet
        following whoever leads now, it does not reclaim leadership."""
        from tpusched.rpc.server import make_server  # tpl: disable=TPL001(cycle: rpc.server imports this module at top, and client imports server back)

        if i not in self._dead:
            raise RuntimeError(f"replica {i} is not dead")
        server, port, svc = make_server(
            f"127.0.0.1:{self.ports[i]}", role=role, **self._make_kw
        )
        if port != self.ports[i]:
            raise RuntimeError(f"could not rebind port {self.ports[i]}")
        server.start()
        self.servers[i] = server
        self.services[i] = svc
        self._dead.discard(i)
        if role == "standby":
            self.followers[i] = StandbyFollower(
                svc, self._peer_addresses(i), poll_s=self._poll_s,
                follower_id=f"replica-{i}", timeout=self._follower_timeout,
            ).start()

    def takeovers(self) -> int:
        return sum(svc.takeovers for svc in self.services)

    def close(self) -> None:
        for i in range(len(self.servers)):
            self.kill(i)
